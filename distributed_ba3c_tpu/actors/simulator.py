"""Simulator processes and the master's receive loop (ZMQ experience plane).

Reference equivalent: ``tensorpack/RL/simulator.py`` — ``SimulatorProcess``,
``SimulatorMaster``, ``ClientState``, ``TransitionExperience`` (SURVEY.md §2.3
#8-9, call stack §3.2). Wire protocol, kept byte-compatible in spirit:

    sim -> master (PUSH -> PULL):  msgpack [ident, state u8-array, reward, isOver]
    master -> sim (ROUTER -> DEALER ident-routed): msgpack action

Both pipes default to ipc:// within a host; tcp:// works unchanged for
remote actor hosts (the multi-host layout keeps actors host-side and only
gradients on ICI — SURVEY.md §2.12).

The child-process side imports no jax: children must stay lightweight (the
reference ran ~50 per worker; we target hundreds per TPU host).
"""

from __future__ import annotations

import multiprocessing as mp
import os
import queue
import threading
import time
import weakref
from abc import abstractmethod
from typing import Callable, Dict, List, Optional

import numpy as np
import zmq

from distributed_ba3c_tpu import telemetry
from distributed_ba3c_tpu.telemetry import tracing
from distributed_ba3c_tpu.envs.base import RLEnvironment
from distributed_ba3c_tpu.utils import logger, sanitizer
from distributed_ba3c_tpu.utils.concurrency import (
    StoppableThread,
    queue_put_stoppable,
)
from distributed_ba3c_tpu.utils.serialize import (
    CorruptFrameError,
    dumps,
    loads,
    unpack_block,
)


class TransitionExperience:
    """One (state, action, value) awaiting its reward attachment."""

    __slots__ = ("state", "action", "reward", "value", "trace")

    def __init__(self, state, action, value, reward=None, trace=None):
        self.state = state
        self.action = action
        self.value = value
        self.reward = reward
        self.trace = trace  # tracing.TraceRef when this step was sampled


class ClientState:
    """Per-simulator state held by the master, keyed by ZMQ ident."""

    __slots__ = ("memory", "ident", "score", "last_seen", "pending_trace")

    def __init__(self, ident: bytes):
        self.ident = ident
        self.memory: List[TransitionExperience] = []
        self.score = 0.0
        # sampled trace ref parked between receive and the predictor
        # callback (protocol-serialized, see BlockClientState)
        self.pending_trace = None
        # initialized to creation time so a client that NEVER sends again
        # (e.g. resurrected by a late predictor callback after pruning) still
        # ages out instead of being exempt forever. MONOTONIC, not wall
        # clock: an NTP step/suspend would otherwise mass-expire (or
        # immortalize) every actor at once (ba3clint A4 caught this).
        self.last_seen = time.monotonic()


class BlockStep:
    """One lockstep block transition: B states with their chosen actions and
    the rewards/dones that arrive one step later (block wire analogue of
    :class:`TransitionExperience`, but [B]-vectorized)."""

    __slots__ = (
        "states", "actions", "values", "logps", "rewards", "dones", "recv_t",
        "trace",
    )

    def __init__(self, states, actions, values, logps):
        self.states = states      # [B, H, W, hist] u8 (view over the frame)
        self.actions = actions    # [B] i32
        self.values = values      # [B] f32
        self.logps = logps        # [B] f32
        self.rewards = None       # [B] f32, attached by the NEXT message
        self.dones = None         # [B] bool, attached by the NEXT message
        # birth stamp for the e2e env-step -> train-ingest latency series
        # (one monotonic per BLOCK step, not per env — telemetry budget);
        # 0.0 when disabled so the overhead gate's off arm runs the true
        # pre-telemetry hot path (flush sites skip the observe on falsy)
        self.recv_t = time.monotonic() if telemetry.enabled() else 0.0
        # tracing.TraceRef when this step was 1-in-N sampled (None for the
        # untraced (N-1)/N — the flush sites branch on None, never on the
        # sampling math)
        self.trace = None


class BlockStatesView:
    """Lazy channel-last ``[B, H, W, hist]`` states over a shm ring window.

    The block-shm wire ships only the NEWEST obs plane per step; the master
    rebuilds each step's stacked state from ``hist`` consecutive ring slots
    — as views, never as copies, on the hot path. Materialization (the one
    unavoidable channel interleave) happens only where the bytes are
    actually consumed: ``__array__`` for a device dispatch, ``__getitem__``
    per datapoint at the feed's collate.

    ``ages[j]`` = env j's steps since episode reset at THIS step. Envs
    younger than ``hist-1`` have missing history planes, which
    HistoryFramePlayer semantics define as zero — those rows take a small
    copy-and-zero path; everything else stays a view. The window view stays
    valid until the ring wraps onto its slots, which the master's attach-
    time capacity check makes unreachable while consumers keep draining
    (utils/shm.py safety contract).
    """

    __slots__ = ("window", "ages", "shape")

    def __init__(self, window: np.ndarray, ages: np.ndarray):
        self.window = window  # [hist, B, H, W] (ring view, or small copy)
        self.ages = ages      # [B] i64 snapshot for this step
        hist, b, h, w = window.shape
        self.shape = (b, h, w, hist)

    def __len__(self) -> int:
        return self.shape[0]

    @property
    def dtype(self):
        return self.window.dtype

    def __array__(self, dtype=None, copy=None):
        hist = self.window.shape[0]
        # sanctioned materialization: __array__ IS the one copy a consumer
        # that needs the whole block pays (the staged path calls
        # materialize_into instead, so the bytes land in a reused buffer)
        out = np.ascontiguousarray(self.window.transpose(1, 2, 3, 0))  # ba3clint: disable=A13
        for j in np.nonzero(self.ages < hist - 1)[0]:
            out[j, :, :, : hist - 1 - int(self.ages[j])] = 0
        if dtype is not None and dtype != out.dtype:
            out = out.astype(dtype)
        return out

    def materialize_into(self, out: np.ndarray) -> np.ndarray:
        """The ``__array__`` interleave written into a PREALLOCATED buffer
        (data/staging.py): zero allocations, one copy pass — the channel
        interleave happens during the write into ``out``."""
        hist = self.window.shape[0]
        np.copyto(out, self.window.transpose(1, 2, 3, 0))
        for j in np.nonzero(self.ages < hist - 1)[0]:
            out[j, :, :, : hist - 1 - int(self.ages[j])] = 0
        return out

    def __getitem__(self, j: int) -> np.ndarray:
        hist = self.window.shape[0]
        age = int(self.ages[j])
        if age >= hist - 1:
            return self.window[:, j].transpose(1, 2, 0)  # zero-copy view
        # young env: the zeroed history planes need a (small) private copy
        arr = np.ascontiguousarray(self.window[:, j].transpose(1, 2, 0))
        arr[..., : hist - 1 - age] = 0
        return arr


class SegStates:
    """Lazy ``[T, H, W, hist]`` states of ONE env column over T block steps.

    What a V-trace segment's ``"state"`` used to be was
    ``np.stack([st.states[j] for st in seg])`` — a full obs copy paid on
    the MASTER thread at every flush, before collate copied the same
    bytes again. This wrapper defers that materialization to wherever the
    bytes are actually consumed: ``materialize_into`` writes the column
    straight into a staging stripe (data/staging.py — the ingest path's
    ONE copy), ``__array__`` keeps every legacy consumer (the compat
    collate's stack, the pod shipper's wire pack) byte-identical.

    Ring-safety: holding per-step states (ring window views on the
    block-shm wire) until collate is exactly what utils/shm.py's capacity
    formula already budgets — a queued segment counts
    ``ring_steps_per_item = unroll_len`` ring steps, which covers the
    whole [s, s+T] span these references pin.
    """

    __slots__ = ("states", "j", "shape")

    def __init__(self, states: list, j: int):
        self.states = states  # T per-step [B, H, W, hist] state objects
        self.j = int(j)
        self.shape = (len(states), *tuple(np.shape(states[0]))[1:])

    @property
    def dtype(self):
        return getattr(self.states[0], "dtype", np.dtype(np.uint8))

    def __len__(self) -> int:
        return self.shape[0]

    def __array__(self, dtype=None, copy=None):
        out = np.stack([s[self.j] for s in self.states])  # ba3clint: disable=A13 — the compat materialization itself
        if dtype is not None and dtype != out.dtype:
            out = out.astype(dtype)
        return out

    def materialize_into(self, out: np.ndarray) -> np.ndarray:
        """Write the env column into ``out[T, H, W, hist]`` (a staging
        stripe view): one pass, no intermediate stack."""
        j = self.j
        for t, s in enumerate(self.states):
            out[t] = s[j]
        return out


class BlockClientState:
    """Per-BLOCK state: one env-server process = one wire client = B envs.

    Heartbeat/prune happen at this granularity (one ``last_seen`` per
    block — a server is alive or dead as a unit), while the experience
    buffers stay per-env: ``steps`` is the block's shared lockstep history
    and ``start[j]`` indexes each env's first unflushed transition in it
    (envs desynchronize only at episode boundaries / n-step truncations).
    ``ring``/``ages`` are used only by the block-shm wire.
    """

    __slots__ = (
        "ident", "n_envs", "scores", "steps", "start", "last_seen",
        "ring", "ages", "last_step", "pending_trace",
    )

    def __init__(self, ident: bytes, n_envs: int):
        self.ident = ident
        self.n_envs = n_envs
        self.scores = np.zeros(n_envs, np.float64)  # RAW episode scores
        self.steps: List[BlockStep] = []
        self.start = np.zeros(n_envs, np.int64)
        self.last_seen = time.monotonic()
        self.ring = None  # utils.shm.ShmRing once attached (block-shm wire)
        self.ages = np.full(n_envs, -1, np.int64)  # -1: first state pending
        # newest wire step seen; a step that goes BACKWARDS means the server
        # restarted under this ident (master resets the incarnation)
        self.last_step = -1
        # the current message's decoded trace ref, parked here between the
        # receive loop and the predictor callback that creates its
        # BlockStep (safe: the lockstep protocol admits no second message
        # from this ident until that callback ran — the same argument the
        # A3 suppressions on the callbacks make)
        self.pending_trace = None

    def close(self) -> None:
        if self.ring is not None:
            self.ring.close()
            self.ring = None


def default_pipes(name: str = "ba3c") -> tuple[str, str]:
    """ipc:// pipe pair for one host (unique per pid so tests can nest)."""
    base = f"ipc:///tmp/{name}-{os.getpid()}"
    return f"{base}-c2s", f"{base}-s2c"


_spawn_ctx = mp.get_context("spawn")


def _decode_action(raw: bytes, fallback, counter):
    """Decode an action reply; junk must not kill the lockstep loop.

    A corrupt reply frame is the master's bug (or the network's), not a
    reason to lose this simulator's episode state (PR 14 class): repeat
    the previous action, make the drop visible on the
    ``corrupt_action_replies_total`` counter, and keep stepping.
    """
    try:
        return loads(raw)
    except Exception:
        counter.inc()
        return fallback


class SimulatorProcess(_spawn_ctx.Process):  # type: ignore[name-defined]
    """One OS process owning one player; loop: send state, await action, step.

    Reference: ``SimulatorProcess._run`` (SURVEY.md §3.2). ``build_player``
    must be picklable (a top-level function or functools.partial).

    Spawned (not forked): the trainer process is multithreaded (JAX runtime,
    predictor, master) and ``fork()`` from a threaded parent can deadlock the
    child. Child processes import only numpy/zmq modules, never jax.
    """

    def __init__(
        self,
        idx: int,
        pipe_c2s: str,
        pipe_s2c: str,
        build_player: Callable[[int], RLEnvironment],
    ):
        super().__init__(daemon=True, name=f"simulator-{idx}")
        self.idx = idx
        self.c2s = pipe_c2s
        self.s2c = pipe_s2c
        self._build_player = build_player

    def run(self) -> None:
        player = self._build_player(self.idx)
        ident = f"simulator-{self.idx}".encode()
        context = zmq.Context()
        c2s = context.socket(zmq.PUSH)
        c2s.setsockopt(zmq.IDENTITY, ident)
        c2s.set_hwm(4)
        c2s.connect(self.c2s)
        s2c = context.socket(zmq.DEALER)
        s2c.setsockopt(zmq.IDENTITY, ident)
        s2c.connect(self.s2c)

        # child-side telemetry: counters + the piggyback tracker (fleet
        # aggregation, telemetry/wire.py). Disabled (BA3C_TELEMETRY=0) the
        # wire stays at its old 4-element message format. SAME series as
        # the C++ env servers' _tele_setup (envs/native.py) — the fleet
        # aggregation must not depend on which sender type a run uses.
        tele = telemetry.registry("simulator")
        c_steps = tele.counter("env_steps_total")
        c_eps = tele.counter("episodes_total")
        c_rew_pos = tele.counter("reward_pos_sum")
        c_rew_neg = tele.counter("reward_neg_sum")
        c_bad = tele.counter("corrupt_action_replies_total")
        tracker = telemetry.DeltaTracker(tele)

        state = player.current_state()
        reward, is_over = 0.0, False
        action = 0  # repeated on a corrupt reply (see _decode_action)
        step = 0
        env_us = 0  # last env-step duration, shipped in the trace context
        try:
            while True:
                msg = [ident, state, reward, is_over]
                d = None
                if (
                    telemetry.enabled()
                    and step and step % telemetry.PIGGYBACK_EVERY == 0
                ):
                    d = tracker.deltas() or None
                # length-versioned tail: deltas 5th element, sampled trace
                # context 6th (THE one layout implementation — tracing.py)
                tracing.stamp_wire_meta(msg, ident, step, d, env_us)
                c2s.send(dumps(msg))
                action = _decode_action(s2c.recv(), action, c_bad)
                t_env = tracing.now_us() if tracing.enabled() else 0
                reward, is_over = player.action(action)
                c_steps.inc()
                if is_over:
                    c_eps.inc()
                # sign-split like native.py: both halves stay monotonic
                if reward > 0:
                    c_rew_pos.inc(reward)
                elif reward < 0:
                    c_rew_neg.inc(-reward)
                state = player.current_state()
                if t_env:
                    env_us = tracing.now_us() - t_env
                step += 1
        except (KeyboardInterrupt, zmq.ContextTerminated):
            pass
        finally:
            c2s.close(0)
            s2c.close(0)
            context.term()


class SimulatorMaster(threading.Thread):
    """Master thread: multiplexes all simulators, dispatches subclass hooks.

    Reference: ``SimulatorMaster.run`` (SURVEY.md §3.2) — attach the incoming
    reward to the previous transition, fire ``_on_episode_over`` /
    ``_on_datapoint``, then ``_on_state`` for the fresh state. A dedicated
    send thread drains ``send_queue`` so predictor callbacks never block on
    the socket.
    """

    def __init__(
        self,
        pipe_c2s: str,
        pipe_s2c: str,
        actor_timeout: Optional[float] = None,
        reward_clip: float = 0.0,
        tele_role: str = "master",
    ):
        """``actor_timeout``: seconds of silence after which a client's state
        is dropped (failure detection the reference lacked, SURVEY.md §5 —
        a dead simulator would otherwise pin its half-built rollout forever).
        None disables pruning. ``reward_clip``: clip the LEARNING reward to
        [-c, c] (0 = off); episode scores always accumulate raw rewards.
        ``tele_role``: this master's telemetry identity — ``master`` for a
        single-fleet run, ``telemetry.fleet_role("master", k)`` when a
        learner hosts several fleets side by side (each master must own its
        counters/gauges, or K masters' series collapse into one registry
        and every per-fleet signal — autoscaler fill fractions included —
        reads the fleet SUM)."""
        super().__init__(daemon=True, name=f"SimulatorMaster-{tele_role}")
        self.actor_timeout = actor_timeout
        assert reward_clip >= 0, (
            f"reward_clip must be >= 0, got {reward_clip} (a negative bound "
            "would silently map every learning reward to a constant)"
        )
        self.reward_clip = reward_clip
        self._last_prune = 0.0
        self.context = zmq.Context()
        self.c2s_socket = self.context.socket(zmq.PULL)
        self.c2s_socket.bind(pipe_c2s)
        self.c2s_socket.set_hwm(32)
        self.s2c_socket = self.context.socket(zmq.ROUTER)
        # identity HANDOVER: a respawned env server reconnects with its
        # dead predecessor's DEALER identity (slot-stable idents are what
        # make restarts land as incarnation resets). Without handover,
        # libzmq keeps the identity bound to the old half-dead pipe and
        # REJECTS the new peer — the master's action replies then go
        # nowhere and the respawned server parks in recv() forever (found
        # by the chaos bench: under sustained kill/respawn every slot
        # wedged one by one until the plane flatlined at zero).
        self.s2c_socket.setsockopt(zmq.ROUTER_HANDOVER, 1)
        self.s2c_socket.bind(pipe_s2c)
        self.s2c_socket.set_hwm(32)

        # sanitizer wrapping (BA3C_SANITIZE=1 in tests): the client table's
        # structure is owned by the receive loop, the send queue has exactly
        # one drain thread — plain defaultdict/Queue when disabled
        self.clients: Dict[bytes, ClientState] = sanitizer.wrap_client_table(
            lambda: ClientState(b""), name="SimulatorMaster.clients"
        )
        self.send_queue: "queue.Queue[list]" = sanitizer.wrap_queue(
            queue.Queue(maxsize=1024), name="SimulatorMaster.send_queue"
        )
        self._stop_evt = threading.Event()
        # block-shm ring sizing inputs (read by _shm_states' attach-time
        # safety check): whoever wires a downstream batcher must declare its
        # collate-holder capacity here — those items left the queue but
        # still pin ring views until collate's np.stack copies them
        self.feed_batch = 0

        # -- telemetry (docs/observability.md): counters are fetched ONCE
        # here and kept as attributes so the hot path pays a dict-get per
        # BATCH, never a registry lookup. Gauges bind weakly — the registry
        # outlives any one master and must not pin a closed one alive.
        self.tele_role = tele_role
        # env-server piggyback deltas fold into the matching fleet role
        # (``fleet`` <-> ``master``, ``fleet.f<k>`` <-> ``master.f<k>``):
        # per-fleet senders must not merge into one aggregate registry
        self._fleet_tele_role = (
            "fleet" if tele_role == "master"
            else tele_role.replace("master", "fleet", 1)
        )
        tele = telemetry.registry(tele_role)
        self._flight = telemetry.flight_recorder()
        self._c_per_env_msgs = tele.counter("per_env_msgs_total")
        self._c_block_msgs = tele.counter("block_msgs_total")
        self._c_block_shm_msgs = tele.counter("block_shm_msgs_total")
        self._c_datapoints = tele.counter("datapoints_total")
        self._c_pruned = tele.counter("clients_pruned_total")
        self._c_dropped = tele.counter("clients_dropped_total")
        self._c_rejected = tele.counter("blocks_rejected_total")
        # integrity rejects get their OWN typed counter next to the
        # structural one: a CRC mismatch means bytes changed in flight
        # (netchaos corruption, a flaky NIC), not a version-skewed sender —
        # the operator runbook branches on exactly this distinction
        self._c_corrupt = tele.counter("corrupt_frames_total")
        self._c_incarnation = tele.counter("incarnation_resets_total")
        self._c_blocked_puts = tele.counter("queue_blocked_puts_total")
        self._h_put_wait = tele.histogram("queue_put_wait_s", unit=1e-6)
        self._h_ingest = tele.histogram("e2e_ingest_latency_s", unit=1e-6)
        # SLO-serving fallback accounting (docs/serving.md): rows answered
        # with the uniform-random fallback after the predictor shed the
        # task (deadline/queue_full typed reject)
        self._c_shed_fallbacks = tele.counter("predictor_shed_fallbacks_total")
        # uniform-fallback RNG for shed replies; sheds can be delivered
        # from the admitting thread AND the predictor scheduler thread, and
        # numpy Generators are not thread-safe — same locking convention as
        # the predictor's PRNG key
        self._shed_rng = np.random.default_rng(0)
        self._shed_lock = threading.Lock()
        ref = weakref.ref(self)
        tele.gauge(
            "clients", fn=lambda: len(m.clients) if (m := ref()) else 0
        )
        tele.gauge(
            "send_queue_depth",
            fn=lambda: m.send_queue.qsize() if (m := ref()) else 0,
        )
        # subclasses create self.queue after super().__init__ — read late
        tele.gauge(
            "train_queue_depth",
            fn=lambda: (
                q.qsize()
                if (m := ref()) and (q := getattr(m, "queue", None))
                else 0
            ),
        )
        # capacity next to depth: an autoscaler (or any scraper) reading
        # queue fill over HTTP needs both ends of the fraction on the
        # endpoint — depth alone is meaningless without the bound
        tele.gauge(
            "train_queue_capacity",
            fn=lambda: (
                int(getattr(q, "maxsize", 0) or 0)
                if (m := ref()) and (q := getattr(m, "queue", None))
                else 0
            ),
        )
        tele.gauge(
            "block_backlog_steps",
            fn=lambda: max(
                (
                    len(c.steps)
                    for c in list(getattr(ref(), "clients", {}).values())
                    if isinstance(c, BlockClientState)
                ),
                default=0,
            ),
        )

        def send_loop():
            t = threading.current_thread()
            assert isinstance(t, StoppableThread)
            while not t.stopped():
                msg = t.queue_get_stoppable(self.send_queue, timeout=0.2)
                if msg is None:
                    return
                try:
                    # ROUTER sends never block: an unroutable ident or a
                    # peer past its HWM DROPS the message (MANDATORY off)
                    # — bounded by construction, not by timeout
                    self.s2c_socket.send_multipart(msg)  # ba3clint: disable=A12 — ROUTER drops, never parks
                except zmq.ZMQError:
                    if t.stopped() or self._stop_evt.is_set():
                        return  # socket closed during teardown
                    raise

        self.send_thread = StoppableThread(
            target=send_loop, daemon=True, name="SimulatorMaster-send"
        )
        self.send_thread.start()

    def run(self) -> None:
        poller = zmq.Poller()
        poller.register(self.c2s_socket, zmq.POLLIN)
        # this receive loop is the structural owner of the client table;
        # the sanitizer (when enabled) flags any other thread that
        # creates/deletes entries
        sanitizer.claim_owner(self.clients)

        try:
            while not self._stop_evt.is_set():
                # prune on EVERY iteration (it self-rate-limits): gating it
                # on poll timeouts would starve pruning exactly when the
                # surviving actors keep the socket busy
                self._prune_dead_actors()
                if not poller.poll(timeout=200):
                    continue
                # wire autodetect per message: the per-env protocol is ONE
                # msgpack frame, the block protocol is multipart — so block
                # and per-env speakers can share the same pipe pair (mixed
                # fleets, rolling upgrades). copy=False: the payload frames
                # back the numpy views directly (zero-copy ingest).
                frames = self.c2s_socket.recv_multipart(copy=False)
                if len(frames) == 1:
                    try:
                        msg = loads(frames[0].buffer)
                        ident, state, reward, is_over = msg[:4]
                    except CorruptFrameError as e:
                        # typed integrity reject: the frame's CRC failed —
                        # count it, record it, keep the loop alive (the
                        # lockstep sender re-sends nothing, parks in recv,
                        # and is pruned/respawned like any dead actor)
                        self._c_corrupt.inc()
                        self._flight.record(
                            "corrupt_frame", wire="per-env",
                            error=str(e)[:200],
                        )
                        logger.error("dropping corrupt per-env frame: %s", e)
                        continue
                    except Exception as e:
                        # untrusted wire input (msgpack raises its own
                        # hierarchy): a malformed per-env frame must not
                        # kill the receive loop for every healthy client —
                        # same posture as the block decoder below
                        self._c_rejected.inc()
                        self._flight.record(
                            "per_env_reject", error=str(e)[:200]
                        )
                        logger.error(
                            "dropping undecodable per-env message: %s", e
                        )
                        continue
                    if len(msg) > 4:
                        # length-versioned header: element 5 is the sender's
                        # piggybacked metric deltas (telemetry/wire.py);
                        # plain 4-element messages parse as before
                        telemetry.apply_fleet_deltas(
                            ident, msg[4], role=self._fleet_tele_role
                        )
                    self._c_per_env_msgs.inc()
                    client = self.clients[ident]
                    client.ident = ident
                    client.last_seen = time.monotonic()
                    if len(msg) > 5:
                        # element 6 is a sampled trace context (tracing.py):
                        # handshake the sender's clock, synthesize the
                        # env_step + wire spans, park the ref for the
                        # predictor callback's transition record
                        client.pending_trace = self._recv_trace(ident, msg[5])
                    self._on_message(ident, state, reward, is_over)
                else:
                    self._on_block_frames(frames)
        except zmq.ContextTerminated:
            logger.info("SimulatorMaster context terminated")
        except zmq.ZMQError:
            # teardown race: close() destroyed the sockets while we polled.
            # Only swallow when shutting down — a live-loop ZMQError is a bug.
            if not self._stop_evt.is_set():
                raise
            logger.info("SimulatorMaster socket closed during shutdown")

    #: how many env transitions one train-queue item represents — the
    #: conversion factor a fleet_snapshot consumer needs to turn queue
    #: depth into a sample backlog. (The shipped autoscaler policy works
    #: on the unit-free fill fraction and does not need it; external
    #: scrapers comparing depth against batch sizes do.) Subclasses own
    #: the real value: BA3C 1 datapoint per item, V-trace unroll_len.
    queue_samples_per_item: int = 1

    def fleet_snapshot(self) -> dict:
        """Fleet-size introspection hook (orchestrate/autoscaler.py).

        One consistent read of the backpressure signals the autoscaler
        feeds on, taken from the SAME telemetry counters the scrape
        endpoint exports — the supervisor acts on the master's account of
        the fleet, never on its own duplicate heartbeats. Safe from any
        thread: every field is a GIL-atomic read or a sharded-counter sum.
        """
        q = getattr(self, "queue", None)
        return {
            "clients": len(self.clients),
            "queue_depth": int(q.qsize()) if q is not None else 0,
            "queue_maxsize": int(getattr(q, "maxsize", 0) or 0),
            "queue_samples_per_item": int(self.queue_samples_per_item),
            "blocked_puts_total": float(self._c_blocked_puts.value()),
            "datapoints_total": float(self._c_datapoints.value()),
        }

    def _prune_dead_actors(self) -> None:
        """Drop state of clients silent for > actor_timeout (actor loss is
        tolerated: its partial rollout is discarded, training continues)."""
        if self.actor_timeout is None:
            return
        now = time.monotonic()
        if now - self._last_prune < self.actor_timeout / 4:
            return
        self._last_prune = now
        dead = [
            ident
            for ident, c in self.clients.items()
            if now - c.last_seen > self.actor_timeout
        ]
        # account FIRST, remove LAST: anything polling the client table
        # (the prune tests, a scrape of the clients gauge) must find the
        # counter ticked and the postmortem on disk by the time the client
        # is gone — the reverse order races every observer
        for ident in dead:
            client = self.clients[ident]
            self._c_pruned.inc()
            self._flight.record(
                "prune",
                ident=repr(ident),
                silent_s=round(now - client.last_seen, 3),
                block=isinstance(client, BlockClientState),
            )
            logger.warn(
                "actor %s silent for >%.0fs — dropped its client state",
                ident,
                self.actor_timeout,
            )
        if dead:
            # a prune IS the postmortem moment: the next wedged multi-hour
            # run must find evidence on disk, not in a truncated log
            self._flight.dump("actor prune")
        for ident in dead:
            client = self.clients.pop(ident)
            if isinstance(client, BlockClientState):
                client.close()  # release the shm ring mapping, if any

    def _on_message(self, ident: bytes, state, reward: float, is_over: bool) -> None:
        """Handle one simulator message (overridable; runs in master thread).

        Default semantics: attach the reward to the previous transition, fire
        the episode/datapoint hooks, then request an action for the new state.
        Per-client ordering is serialized by the protocol — the simulator
        blocks on its action, so no second message from ``ident`` can arrive
        before ``_on_state``'s callback has run.
        """
        client = self.clients[ident]
        if len(client.memory) > 0:
            client.memory[-1].reward = self._learn_reward(reward)
            client.score += reward  # scores stay RAW
            if is_over:
                self._on_episode_over(ident)
            else:
                self._on_datapoint(ident)
        self._on_state(state, ident)

    def _learn_reward(self, reward: float) -> float:
        """The LEARNING reward: clipped to [-c, c] when reward_clip is set
        (single definition shared by every master subclass)."""
        c = self.reward_clip
        return max(-c, min(c, reward)) if c else reward

    def _learn_reward_block(self, rewards: np.ndarray) -> np.ndarray:
        """[B]-vectorized :meth:`_learn_reward` (same clip, one np op)."""
        c = self.reward_clip
        return np.clip(rewards, -c, c) if c else rewards

    # -- block wire ingest (docs/actor_plane.md) ---------------------------
    def _on_block_frames(self, frames) -> None:
        """Decode one block message and dispatch the block hooks.

        Two frame layouts, distinguished by frame count:

        - 4 frames (``block``): ``[header, obs[hist,B,H,W] u8, rewards[B]
          f32, dones[B] u8]``. The obs frame is consumed as a TRANSPOSED
          VIEW ([B,H,W,hist] channel-last, what the net eats).
        - 3 frames (``block-shm``): ``[header, rewards, dones]`` with the
          header naming a /dev/shm ring + this step's slot; states become a
          lazy :class:`BlockStatesView` over the ring window.

        Neither wire ever materializes the channel interleave on the hot
        path; the one real copy happens at device ingest (or the feed's
        collate).
        """
        bufs = [f.buffer for f in frames]
        try:
            if len(bufs) == 4:
                meta, (obs, rewards, dones) = unpack_block(bufs)
                base_meta_len = 3  # [ident, step, B]
                self._c_block_msgs.inc()
            else:
                meta, (rewards, dones) = unpack_block(bufs)
                obs = None
                base_meta_len = 8  # [ident, step, B, ring, cap, h, w, hist]
                self._c_block_shm_msgs.inc()
            ident, step, n_envs = bytes(meta[0]), int(meta[1]), int(meta[2])
            if rewards.shape != (n_envs,) or dones.shape != (n_envs,):
                raise ValueError(
                    f"block payload shapes {rewards.shape}/{dones.shape} "
                    f"do not match header n_envs={n_envs}"
                )
            if len(meta) > base_meta_len:
                # length-versioned header: element base+1 is the server's
                # piggybacked metric deltas (telemetry/wire.py); old
                # base-length headers parse exactly as before. A sampled
                # step appends a SECOND element — the trace context
                # (tracing.py) — after a (possibly empty) deltas dict, so
                # positions never shift under either feature alone.
                telemetry.apply_fleet_deltas(
                    ident, meta[base_meta_len], role=self._fleet_tele_role
                )
            trace_elem = (
                meta[base_meta_len + 1]
                if len(meta) > base_meta_len + 1 else None
            )
        except CorruptFrameError as e:
            # typed integrity reject (CRC mismatch — bytes changed in
            # flight): its own counter + flight kind so operators can tell
            # link corruption from sender version skew; never reaches a
            # frombuffer view (serialize.unpack_block verifies first)
            self._c_corrupt.inc()
            self._flight.record(
                "corrupt_frame", wire="block", error=str(e)[:200]
            )
            logger.error("dropping corrupt block frame: %s", e)
            return
        except (ValueError, TypeError, IndexError) as e:
            # wire input is untrusted: a version-mismatched fleet (or any
            # stray sender on the bound port) must not kill the receive
            # loop for every healthy client — skip the message. The sender,
            # if it is a real env server, parks in recv() and gets pruned.
            self._c_rejected.inc()
            self._flight.record("block_reject", error=str(e)[:200])
            logger.error("dropping undecodable block message: %s", e)
            return
        blk = self.clients.get(ident)
        if blk is not None and step <= blk.last_step:
            # step went backwards: a crashed server was RESTARTED under the
            # same ident inside actor_timeout. Its pre-crash state (pending
            # steps awaiting rewards, episode ages, the old ring inode)
            # would misalign every datapoint — drop it and start a fresh
            # incarnation, same semantics as a prune + reconnect.
            self._c_incarnation.inc()
            self._flight.record(
                "incarnation_reset",
                ident=repr(ident), step=step, last_step=blk.last_step,
            )
            logger.warn(
                "block client %s restarted (step %d after %d) — resetting "
                "its state", ident, step, blk.last_step,
            )
            blk.close()
            blk = None
        if blk is None:
            # structural create stays in the master thread (sanitizer-
            # checked); the defaultdict factory would make a per-env
            # ClientState, so block entries are created explicitly
            blk = BlockClientState(ident, n_envs)
            self.clients[ident] = blk
        blk.last_seen = time.monotonic()
        blk.last_step = step
        if trace_elem is not None:
            blk.pending_trace = self._recv_trace(ident, trace_elem)
        dones = dones.astype(bool)
        try:
            if obs is not None:
                # [B,H,W,hist] zero-copy view
                states = obs.transpose(1, 2, 3, 0)
            else:
                states = self._shm_states(blk, meta, step, dones)
            self._on_block_message(ident, states, rewards, dones)
        except (ValueError, NotImplementedError) as e:
            # a misconfigured CLIENT (ring too small for this learner's
            # buffering, or a block speaker against a per-env-only master)
            # must not kill the receive loop for every other client: drop
            # it — the server stays parked in its recv() — and keep serving
            self._c_dropped.inc()
            self._flight.record(
                "client_drop", ident=repr(ident), error=str(e)[:200]
            )
            logger.error(
                "dropping block client %s (it will get no reply and stay "
                "blocked): %s", ident, e,
            )
            del self.clients[ident]
            blk.close()
            self._flight.dump("client drop")

    def _shm_states(self, blk, meta, step: int, dones: np.ndarray):
        """Build the step's lazy states view from the client's shm ring."""
        # meta[2:8] — not full destructuring: a piggybacked header carries
        # one extra telemetry element (telemetry/wire.py)
        n_envs, ring_name, cap, h, w, hist = meta[2:8]
        if blk.ring is None:
            from distributed_ba3c_tpu.utils.shm import ShmRing, min_safe_cap

            # safety contract (utils/shm.py): a datapoint's backing slot
            # must not be reusable while the datapoint can still be alive.
            # A full train queue blocks the master -> action replies stop
            # -> every lockstep server halts within one step, so the live
            # window is bounded by queue depth + the flush horizon.
            q = getattr(self, "queue", None)
            maxsize = getattr(q, "maxsize", 0)
            horizon = int(
                getattr(self, "local_time_max", 0)
                or getattr(self, "unroll_len", 0)
            )
            if maxsize <= 0:
                raise ValueError(
                    "block-shm wire needs a BOUNDED train queue: queue "
                    "backpressure is what stops ring slots from being "
                    "overwritten under live datapoints"
                )
            # the live window counts EVERY queued-or-held item that can pin
            # a ring view, in ring STEPS: queue items plus the downstream
            # feed's collate holder (outside the queue, still views), each
            # spanning ring_steps_per_item steps (1 for BA3C datapoints;
            # unroll_len for V-trace segments, whose bootstrap_state view
            # trails the segment head by a whole unroll), plus the unflushed
            # blk.steps horizon and the hist slots a window reaches back —
            # the one formula lives in utils/shm.py, shared with cli.py's
            # ring sizing
            span = int(getattr(self, "ring_steps_per_item", 1))
            feed = int(getattr(self, "feed_batch", 0))
            needed = min_safe_cap(n_envs, maxsize, feed, span, horizon, hist)
            if cap <= needed:
                raise ValueError(
                    f"shm ring cap {cap} too small for train queue "
                    f"maxsize {maxsize} (+{feed} feed holder) x {span} "
                    f"steps/item at B={n_envs} (+{horizon}-step flush "
                    f"horizon): need > {needed:.0f} — shrink the queue or "
                    "pass a larger shm_ring_cap to the env server"
                )
            blk.ring = ShmRing.attach(ring_name, cap, n_envs, h, w)
            self._flight.record(
                "ring_attach", ident=repr(blk.ident),
                ring=str(ring_name), cap=int(cap),
            )
        ring = blk.ring.arr
        slot = step % cap
        if step >= hist - 1 and slot >= hist - 1:
            window = ring[slot - hist + 1 : slot + 1]  # zero-copy view
        else:
            # wrapped (or pre-history) window: small stack copy, ~hist/cap
            # of steps take this path
            window = np.stack(
                [ring[(step - k) % cap] for k in range(hist - 1, -1, -1)]
            )
        ages = np.where(dones, 0, blk.ages + 1)
        blk.ages = ages
        return BlockStatesView(window, ages)

    def _on_block_message(
        self,
        ident: bytes,
        states: np.ndarray,
        rewards: np.ndarray,
        dones: np.ndarray,
    ) -> None:
        """Block analogue of :meth:`_on_message`: attach (rewards, dones) to
        the previous block step, account episode scores, fire the subclass
        flush hook, then request actions for the fresh states. Per-block
        ordering is protocol-serialized exactly like the per-env wire: the
        server blocks on its action reply, so no second message from
        ``ident`` can arrive before ``_on_block_state``'s callback ran.
        """
        blk = self.clients[ident]
        if blk.pending_trace is not None:
            # flight events recorded while this sampled block is being
            # flushed/dispatched (queue_wait stalls, prunes) get stamped
            # with its trace id — postmortem dumps correlate with /trace
            # (telemetry/recorder.py); two thread-local ops, sampled only
            with tracing.trace_scope(blk.pending_trace.trace_id):
                self._dispatch_block(blk, states, rewards, dones, ident)
        else:
            self._dispatch_block(blk, states, rewards, dones, ident)

    def _dispatch_block(self, blk, states, rewards, dones, ident) -> None:
        if blk.steps:
            last = blk.steps[-1]
            last.rewards = self._learn_reward_block(rewards)
            last.dones = dones
            blk.scores += rewards  # scores stay RAW
            if dones.any():
                score_q = getattr(self, "score_queue", None)
                for j in np.nonzero(dones)[0]:
                    if score_q is not None:
                        try:
                            score_q.put_nowait(float(blk.scores[j]))
                        except queue.Full:
                            pass
                blk.scores[dones] = 0.0
            self._on_block_flush(ident)
        self._on_block_state(states, ident)

    def _on_block_state(self, states: np.ndarray, ident: bytes) -> None:
        """Fresh [B,...] states arrived: request B actions in ONE predictor
        call and record the block transition (subclass hook)."""
        raise NotImplementedError(
            f"{type(self).__name__} does not implement the block wire — "
            "run its env servers with wire='per-env'"
        )

    def _on_block_flush(self, ident: bytes) -> None:
        """Rewards/dones were attached to the newest block step: emit any
        completed experience (n-step windows / unroll segments) per env
        (subclass hook)."""
        raise NotImplementedError(
            f"{type(self).__name__} does not implement the block wire — "
            "run its env servers with wire='per-env'"
        )

    def _drop_flushed_prefix(self, blk: BlockClientState) -> None:
        """Free block steps every env has consumed (and their zmq frames)."""
        m = int(blk.start.min())
        if m:
            del blk.steps[:m]
            blk.start -= m

    # -- serving-plane shed fallbacks (docs/serving.md) --------------------
    def _shed_fallback_block(self, cb, k: int):
        """Fallback reply for a shed block task (predict/server.py's typed
        :class:`ShedReject`): answer with uniform-random actions so the
        lockstep server keeps stepping instead of parking in ``recv()``.
        The recorded behavior log-prob IS correct for the fallback policy
        (log 1/A), so V-trace stays exact and BA3C merely learns from a
        few exploratory steps; value 0 is the honest no-estimate."""

        def shed(reject):
            A = int(getattr(self.predictor, "num_actions", 0) or 0)
            if A <= 0:
                # no known action space to fall back to: leave the server
                # to the prune path and the operator to the flight record
                self._flight.record("shed_no_fallback", reason=reject.reason)
                return
            with self._shed_lock:
                acts = self._shed_rng.integers(0, A, k)
            self._c_shed_fallbacks.inc(k)
            cb(
                np.ascontiguousarray(acts, np.int32),
                np.zeros(k, np.float32),
                np.full(k, -np.log(A), np.float32),
            )

        return shed

    def _shed_fallback_row(self, cb):
        """Per-env-wire analogue of :meth:`_shed_fallback_block`."""

        def shed(reject):
            A = int(getattr(self.predictor, "num_actions", 0) or 0)
            if A <= 0:
                self._flight.record("shed_no_fallback", reason=reject.reason)
                return
            with self._shed_lock:
                a = int(self._shed_rng.integers(0, A))
            self._c_shed_fallbacks.inc()
            cb(a, 0.0, float(-np.log(A)))

        return shed

    def _recv_trace(self, ident: bytes, trace_elem):
        """Decode one received trace-context element (tracing.py).

        Handshakes the sender's monotonic clock, synthesizes the sender's
        ``env_step`` span (duration shipped in the context — env servers
        never expose a scrape endpoint) plus the ``wire`` transit span,
        and returns a TraceRef for this master's own hops — or None on
        junk (wire input is untrusted, the block decoder's posture)."""
        out = tracing.receive_context(
            tracing.decode_context(trace_elem),
            peer=repr(ident), role=self.tele_role, origin_always=True,
        )
        if out is None:
            return None
        trace_id, parent = out
        return tracing.TraceRef(trace_id, parent)

    def send_action(self, ident: bytes, action: int) -> None:
        self._put_stoppable(self.send_queue, [ident, dumps(int(action))])

    def send_block_actions(self, ident: bytes, actions: np.ndarray) -> None:
        """One batched action reply for a whole block: raw int32[B] frame
        (the server ``np.frombuffer``s it — no msgpack on the reply side)."""
        self._put_stoppable(
            self.send_queue,
            [ident, np.ascontiguousarray(actions, np.int32).tobytes()],
        )

    def _put_stoppable(self, q: queue.Queue, item, timeout: float = 0.5) -> bool:
        """Backpressure that stays shutdown-responsive: bounded-timeout puts
        re-checking the stop flag (the plane's only sanctioned blocking put —
        ba3clint A2). Returns False if the master stopped while waiting.

        Telemetry rides the SLOW path only: the common non-blocked put is
        one ``put_nowait`` (same cost as before); a put that actually hits
        backpressure pays two monotonic reads against a wait that is always
        orders of magnitude longer."""
        if self._stop_evt.is_set():
            # the fast path must not outlive stop(): flush loops abort on
            # the first False, same as queue_put_stoppable's own guard
            return False
        try:
            q.put_nowait(item)
            return True
        except queue.Full:
            pass
        self._c_blocked_puts.inc()
        t0 = time.monotonic()
        ok = queue_put_stoppable(q, item, self._stop_evt, timeout)
        waited = time.monotonic() - t0
        self._h_put_wait.observe(waited)
        if waited >= 0.05:
            # the flight ring wants stalls, not the steady-state jitter
            self._flight.record("queue_wait", wait_s=round(waited, 4))
        return ok

    def stop(self) -> None:
        self._stop_evt.set()
        self.send_thread.stop()

    def close(self) -> None:
        """Stop threads and tear down ZMQ without lingering sends.

        Idempotent; joins the receive loop BEFORE destroying the context so
        no ZMQ background thread outlives the master (a leaked io-thread can
        wedge later in-process jit dispatch — the round-1 pytest deadlock).
        """
        self._stop_evt.set()
        self.send_thread.stop()
        self.send_thread.join(timeout=2)
        if self.is_alive():
            self.join(timeout=2)
        try:
            self.context.destroy(linger=0)
        except zmq.ZMQError:
            pass  # already destroyed
        for client in list(self.clients.values()):
            if isinstance(client, BlockClientState):
                client.close()  # release shm ring mappings, if any

    @abstractmethod
    def _on_state(self, state, ident: bytes) -> None:
        """A fresh state arrived: request an action and record the transition."""

    @abstractmethod
    def _on_episode_over(self, ident: bytes) -> None:
        """The client's episode ended (reward already attached)."""

    @abstractmethod
    def _on_datapoint(self, ident: bytes) -> None:
        """A mid-episode transition completed (reward already attached)."""

    def __del__(self):
        try:
            self._stop_evt.set()
            self.send_thread.stop()
            self.context.destroy(0)
        except Exception:
            pass
