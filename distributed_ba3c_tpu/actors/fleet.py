"""Multi-fleet actor-plane assembly (docs/actor_plane.md, ISSUE 10).

One learner, N independent actor fleets: each fleet is a complete plane —
its own pipe pair, its own master (receive loop + train queue), its own
batched predictor, its own supervisor/autoscaler, its own telemetry
identity (``telemetry.fleet_role``) — and the fleet-merge layer
(data/dataflow.py ``FleetMergeFeed``) drains the per-fleet queues into one
macro-batch train stream. Why whole planes instead of one wider plane: the
macro steps (parallel/train_step.py ``make_macro_train_step`` and friends)
shard the FLEET axis over the mesh's data axis, so a data-parallel
deployment assigns fleets — not batch slivers — to chips and every chip
steps at its full-occupancy batch while the per-fleet recipe stays fixed
(the PERF.md 65.6k -> ~38k shard-ladder fix, ROADMAP item 1).

Isolation comes from the addressing scheme, not new machinery:

- **pipes**: :func:`fleet_pipes` derives per-fleet endpoints (fleet 0 keeps
  the base addresses, so single-fleet runs are byte-identical);
- **ring names**: ``utils/shm.py ring_name`` hashes the fleet's c2s
  address, so per-fleet pipes namespace the /dev/shm rings with the SAME
  formula the supervisor reclaims by — nothing new to drift;
- **idents**: callers tag server ident prefixes with ``f<k>-`` so the
  telemetry sender table (telemetry/wire.py) keeps per-fleet senders
  distinct;
- **telemetry**: per-fleet roles ``master.f<k>`` / ``predictor.f<k>`` /
  ``fleet.f<k>`` — the scrape label one ``http_signals`` consumer uses to
  address one master among several on a host.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Callable, List, Optional, Tuple

from distributed_ba3c_tpu import telemetry
from distributed_ba3c_tpu.utils import logger

_TCP_RE = re.compile(r"^(tcp://[^:]+:)(\d+)$")


def fleet_pipes(pipe_c2s: str, pipe_s2c: str, fleet: int) -> Tuple[str, str]:
    """Per-fleet wire addresses derived from the base pipe pair.

    Fleet 0 keeps the base addresses unchanged — a single-fleet run and
    fleet 0 of a multi-fleet run bind identically, so external env-server
    launch lines keep working. ``tcp://host:port`` endpoints step the port
    by ``2 * fleet`` (even stride so the conventional adjacent c2s/s2c
    pair — e.g. 5555/5556 — never collides across fleets; operators open
    the contiguous range); every other transport (ipc://, inproc://) gets
    a ``-f<k>`` path suffix. :func:`build_fleet_planes` validates the
    derived set for collisions, so an unconventional base spacing fails
    loudly at assembly, not as a silent double-bind.
    """
    if fleet == 0:
        return pipe_c2s, pipe_s2c

    def derive(addr: str) -> str:
        m = _TCP_RE.match(addr)
        if m:
            return f"{m.group(1)}{int(m.group(2)) + 2 * fleet}"
        return f"{addr}-f{fleet}"

    return derive(pipe_c2s), derive(pipe_s2c)


class FanoutPredictors:
    """The learner-side facade over K per-fleet predictors.

    ``update_params`` fans the publish out to every fleet WITHOUT
    blocking the caller: one latest-wins pump thread per predictor
    (utils/concurrency.py :class:`LatestWinsPump`), so a slow or wedged
    fleet's predictor stalls only its own pump — never the learner's
    publish path, and never the OTHER fleets' publishes. Skipped
    intermediate versions are correct by construction (latest wins per
    policy: nobody should ever serve a version the learner has already
    superseded) and counted as ``fanout_publishes_coalesced_total``.
    Synchronous reads (``predict_batch`` — the Evaluator path) delegate
    to fleet 0, whose policy is identical after any settled publish.
    ``flush()`` is the barrier for callers that need settledness (tests,
    checkpoint-restore republish); ``close()`` stops the pumps.
    """

    def __init__(self, predictors: List[Any]):
        if not predictors:
            raise ValueError("FanoutPredictors needs at least one predictor")
        self.predictors = list(predictors)
        from distributed_ba3c_tpu.utils.concurrency import LatestWinsPump

        tele = telemetry.registry("learner")
        self._c_publishes = tele.counter("fanout_publishes_total")
        self._c_coalesced = tele.counter("fanout_publishes_coalesced_total")
        self._c_errors = tele.counter("fanout_publish_errors_total")
        # fan-out facade, not a new publish path: the ONE sanctioned
        # caller (Trainer._publish_params) owns the version accounting;
        # the pumps only multiply its publish across fleets
        self._pumps = [
            LatestWinsPump(
                apply=lambda policy, params, _p=pred: _p.update_params(
                    params, policy=policy
                ),
                name=f"param-fanout-{k}",
                on_coalesce=self._c_coalesced.inc,
                on_error=lambda e, _k=k: self._publish_error(_k, e),
            )
            for k, pred in enumerate(self.predictors)
        ]
        for p in self._pumps:
            p.start()

    def _publish_error(self, fleet: int, e: Exception) -> None:
        # a failing publish means this fleet's actors keep sampling a
        # FROZEN policy — counted, flight-recorded AND logged, so the
        # async pump never turns the old synchronous loud-failure path
        # into a silent one
        self._c_errors.inc()
        telemetry.flight_recorder().record(
            "fanout_publish_error", fleet=fleet, error=repr(e)
        )
        logger.error(
            "param fan-out to fleet %d predictor FAILED (its actors are "
            "sampling a stale policy until a publish succeeds): %r",
            fleet, e,
        )

    @property
    def num_actions(self) -> int:
        return self.predictors[0].num_actions

    def update_params(self, params, policy: str = "default") -> None:
        for pump in self._pumps:
            pump.publish(policy, params)
        self._c_publishes.inc()

    def flush(self, timeout: float = 5.0) -> bool:
        """Wait until every fleet applied the latest publish (False if a
        predictor stayed wedged past ``timeout`` — the caller keeps its
        thread either way; that is the whole point of the pumps)."""
        ok = True
        for pump in self._pumps:
            ok = pump.flush(timeout) and ok
        return ok

    # StartProcOrThread protocol: the facade owns pump THREADS now, so it
    # must ride the trainer lifecycle (cli puts it first in startables:
    # the pumps stop before any predictor they publish into does)
    def start(self) -> None:
        """No-op: the pumps spin up in ``__init__`` so pre-train
        publishes (checkpoint restore) already fan out."""

    def stop(self) -> None:
        for pump in self._pumps:
            pump.stop()

    def join(self, timeout: Optional[float] = None) -> None:
        for pump in self._pumps:
            pump.join(timeout)

    def close(self) -> None:
        for pump in self._pumps:
            pump.stop()

    def predict_batch(self, states):
        return self.predictors[0].predict_batch(states)


@dataclasses.dataclass
class FleetPlane:
    """One fleet's assembled plane (what build_fleet_planes returns)."""

    fleet: int
    pipe_c2s: str
    pipe_s2c: str
    predictor: Any
    master: Any
    supervisor: Any = None
    autoscaler: Any = None
    # NOTE deliberately no per-plane startables() convenience: start order
    # is a CROSS-plane contract (every fleet's predictor+master, then the
    # merge feed, then supervisors/autoscalers — spawning any fleet's
    # servers before every master's receive loop is live would park them
    # in their first recv), so the caller assembling all planes owns it
    # (cli.py)


def build_fleet_planes(
    n_fleets: int,
    pipe_c2s: str,
    pipe_s2c: str,
    make_predictor: Callable[[int, str], Any],
    make_master: Callable[[int, str, str, Any, str], Any],
    make_supervision: Optional[
        Callable[[int, str, str, Any], Tuple[Any, Any]]
    ] = None,
) -> List[FleetPlane]:
    """Assemble K per-fleet actor planes behind one learner.

    Factories (all fleet-indexed, handed the derived addresses and the
    fleet's telemetry role):

    - ``make_predictor(fleet, tele_role)`` — the fleet's BatchedPredictor,
      warmed by the caller;
    - ``make_master(fleet, c2s, s2c, predictor, tele_role)`` — the fleet's
      SimulatorMaster subclass (owns its train queue);
    - ``make_supervision(fleet, c2s, s2c, master)`` — optional
      ``(FleetSupervisor, Autoscaler-or-None)`` pair for locally-hosted
      fleets (external fleets pass None and supervise on their own hosts).

    Single-fleet (``n_fleets == 1``) assemblies keep the legacy telemetry
    roles (``master``/``predictor``) so every existing dashboard, signal
    scrape and test reads unchanged; only a real multi-fleet run grows the
    ``.f<k>`` label space.

    This function is the sanctioned multi-fleet spawn point: ba3clint A8
    flags direct calls outside ``orchestrate/`` the same way it flags
    direct env-server construction — cli.py and bench.py carry the
    sanctioned suppressions (factories handed to supervisors, and the raw
    measurand plane).
    """
    if n_fleets < 1:
        raise ValueError(f"n_fleets must be >= 1, got {n_fleets}")
    pipes = [fleet_pipes(pipe_c2s, pipe_s2c, k) for k in range(n_fleets)]
    flat = [a for pair in pipes for a in pair]
    if len(set(flat)) != len(flat):
        raise ValueError(
            f"derived fleet pipe addresses collide across {n_fleets} fleets "
            f"({flat}) — space the base tcp ports at least {2 * n_fleets} "
            "apart between c2s and s2c, or use distinct hosts/paths"
        )
    planes: List[FleetPlane] = []
    for k in range(n_fleets):
        c2s_k, s2c_k = pipes[k]
        tag = k if n_fleets > 1 else None  # single fleet keeps legacy roles
        predictor = make_predictor(k, telemetry.fleet_role("predictor", tag))
        master = make_master(
            k, c2s_k, s2c_k, predictor, telemetry.fleet_role("master", tag)
        )
        supervisor = autoscaler = None
        if make_supervision is not None:
            supervisor, autoscaler = make_supervision(k, c2s_k, s2c_k, master)
        planes.append(
            FleetPlane(
                fleet=k, pipe_c2s=c2s_k, pipe_s2c=s2c_k,
                predictor=predictor, master=master,
                supervisor=supervisor, autoscaler=autoscaler,
            )
        )
    return planes
