"""BA3CSimulatorMaster: the master↔trainer bridge with n-step assembly.

Reference equivalent: ``MySimulatorMaster`` in ``src/train.py`` (SURVEY.md
§2.1 #3, call stack §3.2): on each state request a batched prediction, record
the (state, action, value) transition, and on episode end or
LOCAL_TIME_MAX-truncation fold the client's memory into discounted n-step
returns pushed to the training queue.
"""

from __future__ import annotations

import queue
from typing import Optional

import numpy as np

from distributed_ba3c_tpu.actors.simulator import (
    SimulatorMaster,
    TransitionExperience,
)
from distributed_ba3c_tpu.predict.server import BatchedPredictor
from distributed_ba3c_tpu.utils import sanitizer


class BA3CSimulatorMaster(SimulatorMaster):
    """Feeds the training queue with [state, action, n-step return] triples."""

    def __init__(
        self,
        pipe_c2s: str,
        pipe_s2c: str,
        predictor: BatchedPredictor,
        gamma: float = 0.99,
        local_time_max: int = 5,
        train_queue: Optional[queue.Queue] = None,
        score_queue: Optional[queue.Queue] = None,
        actor_timeout: Optional[float] = None,
        reward_clip: float = 0.0,
    ):
        super().__init__(
            pipe_c2s, pipe_s2c, actor_timeout=actor_timeout,
            reward_clip=reward_clip,
        )
        self.predictor = predictor
        self.gamma = gamma
        self.local_time_max = local_time_max
        # bounded like the reference's FIFOQueue: backpressure pauses actors
        self.queue: queue.Queue = sanitizer.wrap_queue(
            train_queue or queue.Queue(maxsize=4096),
            name="BA3CSimulatorMaster.queue",
        )
        self.score_queue = score_queue

    def _on_state(self, state: np.ndarray, ident: bytes) -> None:
        def cb(action: int, value: float, logp: float):
            client = self.clients[ident]
            # safe cross-thread append: the simulator is blocked awaiting
            # this very action, so the master cannot touch client.memory
            # until send_action below releases it (protocol serialization;
            # the BA3C_SANITIZE=1 job watches the table half of this claim)
            client.memory.append(  # ba3clint: disable=A3
                TransitionExperience(state, action, value)
            )
            self.send_action(ident, action)

        self.predictor.put_task(state, cb)

    def _on_episode_over(self, ident: bytes) -> None:
        client = self.clients[ident]
        if self.score_queue is not None:
            try:
                self.score_queue.put_nowait(client.score)
            except queue.Full:
                pass
        client.score = 0.0
        self._parse_memory(0.0, ident, is_over=True)

    def _on_datapoint(self, ident: bytes) -> None:
        client = self.clients[ident]
        if len(client.memory) == self.local_time_max + 1:
            # bootstrap from the newest transition's value estimate
            self._parse_memory(client.memory[-1].value, ident, is_over=False)

    def _parse_memory(self, init_r: float, ident: bytes, is_over: bool) -> None:
        client = self.clients[ident]
        mem = client.memory
        if not is_over:
            last = mem[-1]
            mem = mem[:-1]
        R = float(init_r)
        for k in reversed(mem):
            R = k.reward + self.gamma * R
            # backpressure pauses actors, but must stay shutdown-responsive
            if not self._put_stoppable(
                self.queue, [k.state, k.action, np.float32(R)]
            ):
                return  # master stopped while the learner was backed up
        client.memory = [] if is_over else [last]
