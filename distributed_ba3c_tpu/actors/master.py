"""BA3CSimulatorMaster: the master↔trainer bridge with n-step assembly.

Reference equivalent: ``MySimulatorMaster`` in ``src/train.py`` (SURVEY.md
§2.1 #3, call stack §3.2): on each state request a batched prediction, record
the (state, action, value) transition, and on episode end or
LOCAL_TIME_MAX-truncation fold the client's memory into discounted n-step
returns pushed to the training queue.
"""

from __future__ import annotations

import queue
import time
from typing import Optional

import numpy as np

from distributed_ba3c_tpu.actors.simulator import (
    BlockClientState,
    BlockStep,
    SimulatorMaster,
    TransitionExperience,
)
from distributed_ba3c_tpu.predict.server import BatchedPredictor
from distributed_ba3c_tpu.utils import sanitizer
from distributed_ba3c_tpu.utils.concurrency import FastQueue


class BA3CSimulatorMaster(SimulatorMaster):
    """Feeds the training queue with [state, action, n-step return] triples."""

    # fleet_snapshot conversion factor: each queued item is ONE
    # (state, action, R) datapoint, so queue depth already IS the sample
    # backlog (actors/simulator.py documents the field's contract)
    queue_samples_per_item = 1

    def __init__(
        self,
        pipe_c2s: str,
        pipe_s2c: str,
        predictor: BatchedPredictor,
        gamma: float = 0.99,
        local_time_max: int = 5,
        train_queue: Optional[queue.Queue] = None,
        score_queue: Optional[queue.Queue] = None,
        actor_timeout: Optional[float] = None,
        reward_clip: float = 0.0,
        tele_role: str = "master",
    ):
        super().__init__(
            pipe_c2s, pipe_s2c, actor_timeout=actor_timeout,
            reward_clip=reward_clip, tele_role=tele_role,
        )
        self.predictor = predictor
        self.gamma = gamma
        self.local_time_max = local_time_max
        # bounded like the reference's FIFOQueue: backpressure pauses
        # actors. FastQueue, not queue.Queue: the block wire pushes 40k+
        # datapoints/s and a mutex+condvar queue costs a futex per op on
        # sandboxed kernels (utils/concurrency.py)
        self.queue: queue.Queue = sanitizer.wrap_queue(
            train_queue or FastQueue(maxsize=4096),
            name="BA3CSimulatorMaster.queue",
        )
        self.score_queue = score_queue

    def _on_state(self, state: np.ndarray, ident: bytes) -> None:
        # claim the receive loop's parked trace ref (tracing.py sampling)
        client0 = self.clients[ident]
        ref, client0.pending_trace = client0.pending_trace, None
        if ref is not None:
            # receive -> dispatch: decode + the PREVIOUS step's flush,
            # including any train-queue backpressure stall — attributed
            # to the master here so it never lands inside the predict
            # spans (the plane exists to point at the right stage)
            ref = ref.hop("master_ingest", self.tele_role)

        def cb(action: int, value: float, logp: float):
            client = self.clients[ident]
            # safe cross-thread append: the simulator is blocked awaiting
            # this very action, so the master cannot touch client.memory
            # until send_action below releases it (protocol serialization;
            # the BA3C_SANITIZE=1 job watches the table half of this claim)
            trace = ref.hop("predict", self.tele_role) if ref else None
            client.memory.append(  # ba3clint: disable=A3
                TransitionExperience(state, action, value, trace=trace)
            )
            self.send_action(ident, action)

        # shed fallback (docs/serving.md): under an SLO'd predictor a shed
        # task answers with a uniform-random action instead of wedging the
        # simulator; without deadlines (the default) it never fires.
        # trace= only when sampled (the duck-typed-predictor contract the
        # block path documents)
        if ref is None:
            self.predictor.put_task(
                state, cb, shed_callback=self._shed_fallback_row(cb)
            )
        else:
            self.predictor.put_task(
                state, cb, shed_callback=self._shed_fallback_row(cb),
                trace=ref,
            )

    def _on_episode_over(self, ident: bytes) -> None:
        client = self.clients[ident]
        if self.score_queue is not None:
            try:
                self.score_queue.put_nowait(client.score)
            except queue.Full:
                pass
        client.score = 0.0
        self._parse_memory(0.0, ident, is_over=True)

    def _on_datapoint(self, ident: bytes) -> None:
        client = self.clients[ident]
        if len(client.memory) == self.local_time_max + 1:
            # bootstrap from the newest transition's value estimate
            self._parse_memory(client.memory[-1].value, ident, is_over=False)

    def _parse_memory(self, init_r: float, ident: bytes, is_over: bool) -> None:
        client = self.clients[ident]
        mem = client.memory
        if not is_over:
            last = mem[-1]
            mem = mem[:-1]
        # a sampled step's trace continues on the FIRST datapoint this
        # flush emits (the per-env mirror of _flush_cohort's claim); the
        # rider is stripped by the feed before collate (data/dataflow.py)
        rider = None
        for k in mem:
            if k.trace is not None:
                rider, k.trace = k.trace.hop("nstep_flush", self.tele_role), None
                break
        R = float(init_r)
        for k in reversed(mem):
            R = k.reward + self.gamma * R
            item = [k.state, k.action, np.float32(R)]
            if rider is not None:
                item.append(rider)
                rider = None
            # backpressure pauses actors, but must stay shutdown-responsive
            if not self._put_stoppable(self.queue, item):
                return  # master stopped while the learner was backed up
        self._c_datapoints.inc(len(mem))  # one batched inc per flush
        client.memory = [] if is_over else [last]

    # -- block wire (one message per env-server per step) ------------------
    def _on_block_state(self, states: np.ndarray, ident: bytes) -> None:
        blk = self.clients[ident]
        # claim the receive loop's parked trace ref (None for the
        # untraced (N-1)/N of steps — tracing.py sampling)
        ref, blk.pending_trace = blk.pending_trace, None
        if ref is not None:
            # receive -> dispatch: decode + the previous step's flush
            # (incl. backpressure stalls) stays a MASTER hop — see
            # _on_state
            ref = ref.hop("master_ingest", self.tele_role)

        def cb(actions: np.ndarray, values: np.ndarray, logps: np.ndarray):
            # safe cross-thread append: the env server is blocked awaiting
            # this very action block, so the master cannot touch blk.steps
            # until send_block_actions below releases it (protocol
            # serialization, same argument as the per-env callback; blk is
            # captured by object so a pruned block is never resurrected
            # through the defaultdict from this thread)
            st = BlockStep(states, actions, values, logps)
            if ref is not None:
                # the serve RTT span (recv -> actions in hand); the
                # predictor's dispatch/fetch sub-spans ride the same trace
                st.trace = ref.hop("predict", self.tele_role)
            blk.steps.append(st)
            self.send_block_actions(ident, actions)

        # same fallback contract as the per-env path: a shed block gets
        # uniform-random actions so the lockstep server never wedges.
        # trace= only when sampled: the common path keeps the exact
        # pre-tracing call (and duck-typed predictors need no new kwarg)
        if ref is None:
            self.predictor.put_block_task(
                states, cb,
                shed_callback=self._shed_fallback_block(cb, len(states)),
            )
        else:
            self.predictor.put_block_task(
                states, cb,
                shed_callback=self._shed_fallback_block(cb, len(states)),
                trace=ref,
            )

    def _on_block_flush(self, ident: bytes) -> None:
        """Per-env n-step emission over the block's shared step list.

        Exactly :meth:`_on_episode_over`/:meth:`_on_datapoint` semantics,
        env-by-env: a done env flushes its whole pending window with R=0; an
        env whose pending window hit ``local_time_max``+1 flushes the first
        ``local_time_max`` transitions bootstrapping from the newest value
        and keeps the newest transition as the next window's head.
        """
        blk: BlockClientState = self.clients[ident]
        t_end = len(blk.steps)
        last = blk.steps[-1]
        dones, values = last.dones, last.values
        T = self.local_time_max
        start = blk.start
        # Episode boundaries leave `start` ragged (each done re-phases its
        # env's n-step window), so the flush runs VECTORIZED PER COHORT:
        # envs sharing a window [s, e) flush together with one f64 return
        # scan (bit-identical to the per-env f64 chain) and bulk-extracted
        # actions — no per-element numpy scalar math on the 40k+
        # datapoints/s path (measured at a third of a core per-element).
        pending = t_end - start
        flush_done = np.nonzero(dones)[0]
        flush_trunc = np.nonzero(~dones & (pending == T + 1))[0]
        for idx, e_off, bootstrap in (
            (flush_done, 0, False),
            (flush_trunc, 1, True),
        ):
            if idx.size == 0:
                continue
            for s in np.unique(start[idx]):
                cohort = idx[start[idx] == s]
                if not self._flush_cohort(
                    blk, cohort, int(s), t_end - e_off,
                    values if bootstrap else None,
                ):
                    return  # master stopped while learner backed up
        start[flush_done] = t_end
        start[flush_trunc] = t_end - 1
        self._drop_flushed_prefix(blk)

    def _flush_cohort(
        self,
        blk: BlockClientState,
        cohort: np.ndarray,
        s: int,
        e: int,
        bootstrap_values,
    ) -> bool:
        """Emit steps [s, e) for the envs in ``cohort``, newest-first
        (matching :meth:`_parse_memory`'s order). ``bootstrap_values``
        is None for episode-end flushes (R starts at 0)."""
        if bootstrap_values is None:
            R = np.zeros(cohort.size, np.float64)
        else:
            R = bootstrap_values[cohort].astype(np.float64)
        g, q, put = self.gamma, self.queue, self._put_stoppable
        js = cohort.tolist()
        for t in range(e - 1, s - 1, -1):
            st = blk.steps[t]
            R = st.rewards[cohort].astype(np.float64) + g * R
            R32 = R.astype(np.float32)
            states = st.states
            acts = st.actions[cohort].tolist()
            # a sampled step's trace continues on the FIRST datapoint its
            # flush emits (one block lifetime = one trace, claimed once —
            # the other B-1 envs share the step but not the trace); the
            # 4th element rides the [state, action, R] item and is
            # stripped by the feed before collate (data/dataflow.py)
            ref, st.trace = st.trace, None
            if ref is not None:
                ref = ref.hop("nstep_flush", self.tele_role)
            for i, j in enumerate(js):
                item = [states[j], acts[i], R32[i]]
                if ref is not None:
                    item.append(ref)
                    ref = None
                if not put(q, item):
                    return False
        # telemetry, batched per cohort (not per datapoint — hot-path
        # budget): datapoint count plus the e2e env-step -> train-ingest
        # latency of the cohort's OLDEST step (the worst case). recv_t is
        # 0.0 when telemetry is disabled — skip the monotonic math so the
        # off mode runs the true pre-telemetry hot path
        self._c_datapoints.inc((e - s) * cohort.size)
        if blk.steps[s].recv_t:
            self._h_ingest.observe(time.monotonic() - blk.steps[s].recv_t)
        return True

