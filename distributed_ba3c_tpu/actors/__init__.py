"""The actor plane: simulator processes + master, ZMQ experience transport.

Reference equivalent: ``src/tensorpack/RL/simulator.py`` +
``predict/concurrency.py`` (SURVEY.md §2.3). The experience plane keeps the
reference's shape — N OS processes streaming (state, reward, isOver) over ZMQ
to one master thread — while action serving collapses into a single batched
device call (predict/server.py).
"""

from distributed_ba3c_tpu.actors.simulator import (
    ClientState,
    SimulatorMaster,
    SimulatorProcess,
    TransitionExperience,
)
from distributed_ba3c_tpu.actors.master import BA3CSimulatorMaster

__all__ = [
    "ClientState",
    "SimulatorMaster",
    "SimulatorProcess",
    "TransitionExperience",
    "BA3CSimulatorMaster",
]
