"""VTraceSimulatorMaster: fixed-length rollout segments with behavior log-probs.

The V-trace learner (parallel/vtrace_step.py) consumes IMPALA-style unrolls:
segments of exactly ``unroll_len`` transitions that run straight across
episode boundaries (``done`` flags mark them; the reverse scan zeroes the
discount there). This differs from :class:`BA3CSimulatorMaster`'s
per-episode n-step flush — static segment shapes are what keep the learner
a single compiled program (no per-length recompiles).

Reference context: no equivalent exists — the reference's async PS updates
tolerate staleness silently (SURVEY.md §3.4); this is the principled TPU-side
replacement (BASELINE.json config #4).
"""

from __future__ import annotations

import queue
import time
from typing import Optional

import numpy as np

from distributed_ba3c_tpu.actors.simulator import (
    BlockClientState,
    BlockStep,
    SegStates,
    SimulatorMaster,
)
from distributed_ba3c_tpu.telemetry import tracing
from distributed_ba3c_tpu.predict.server import BatchedPredictor
from distributed_ba3c_tpu.utils import sanitizer
from distributed_ba3c_tpu.utils.concurrency import FastQueue


class _Step:
    __slots__ = ("state", "action", "logp", "value", "reward", "done",
                 "trace")

    def __init__(self, state, action, logp, value=0.0, trace=None):
        self.state = state
        self.action = action
        self.logp = logp
        # V_mu(s_t) as served — emitted only by record_values masters
        # (the pod's value_lag_mae input); stored always, it is already
        # in the predictor callback's hand
        self.value = value
        self.reward = 0.0
        self.done = False
        self.trace = trace  # tracing.TraceRef when this step was sampled


class VTraceSimulatorMaster(SimulatorMaster):
    """Emits segment dicts onto ``queue``:

    ``{"state": [T,...], "action": [T], "reward": [T], "done": [T],
       "behavior_log_probs": [T], "bootstrap_state": [...]}``

    ``record_values`` (class attribute, default False) adds a
    ``"behavior_values": [T]`` key to every segment — the pod master
    (pod/host.py) flips it for the ``value_lag_mae`` staleness input.
    The V-trace plane keeps it off: its learner feed has no spec for the
    key, and ONE emission path serving both planes is the point (the
    make_finish_update lesson — a flush fix must not diverge by copy).
    """

    record_values = False

    def __init__(
        self,
        pipe_c2s: str,
        pipe_s2c: str,
        predictor: BatchedPredictor,
        unroll_len: int = 5,
        train_queue: Optional[queue.Queue] = None,
        score_queue: Optional[queue.Queue] = None,
        actor_timeout: Optional[float] = None,
        reward_clip: float = 0.0,
        tele_role: str = "master",
    ):
        super().__init__(
            pipe_c2s, pipe_s2c, actor_timeout=actor_timeout,
            reward_clip=reward_clip, tele_role=tele_role,
        )
        self.predictor = predictor
        self.unroll_len = unroll_len
        # each queued segment's bootstrap_state pins a block-shm ring view
        # that trails the newest written slot by a whole unroll — the ring
        # safety check must count T steps per queued item, not 1
        self.ring_steps_per_item = unroll_len
        # fleet_snapshot conversion factor: a queued item is a whole
        # unroll segment, so a consumer turning depth into a sample
        # backlog must multiply by unroll_len — reading a V-trace queue
        # as single datapoints undercounts it T-fold (actors/simulator.py
        # documents the field's contract)
        self.queue_samples_per_item = unroll_len
        # FastQueue for the same reason as BA3CSimulatorMaster: segment
        # emission rides the block wire's datapoint budget
        self.queue: queue.Queue = sanitizer.wrap_queue(
            train_queue or FastQueue(maxsize=1024),
            name="VTraceSimulatorMaster.queue",
        )
        self.score_queue = score_queue

    def _on_state(self, state: np.ndarray, ident: bytes) -> None:
        # claim the receive loop's parked trace ref (tracing.py sampling)
        client0 = self.clients[ident]
        ref, client0.pending_trace = client0.pending_trace, None
        if ref is not None:
            # receive -> dispatch: decode + previous-step flush (incl.
            # backpressure stalls) stays a MASTER hop, never inside the
            # predict spans (BA3CSimulatorMaster._on_state documents why)
            ref = ref.hop("master_ingest", self.tele_role)

        def cb(action: int, value: float, logp: float):
            client = self.clients[ident]
            # safe cross-thread append: the simulator is blocked awaiting
            # this very action, so the master cannot reslice client.memory
            # until send_action below releases it (protocol serialization;
            # the BA3C_SANITIZE=1 job watches the table half of this claim)
            trace = ref.hop("predict", self.tele_role) if ref else None
            client.memory.append(_Step(state, action, logp, value, trace))  # ba3clint: disable=A3
            self.send_action(ident, action)

        # shed fallback (docs/serving.md): the uniform logp the fallback
        # records is the TRUE behavior policy, so V-trace stays exact.
        # trace= only when sampled: the common path keeps the exact
        # pre-tracing call (and duck-typed predictors need no new kwarg)
        if ref is None:
            self.predictor.put_task(
                state, cb, shed_callback=self._shed_fallback_row(cb)
            )
        else:
            self.predictor.put_task(
                state, cb, shed_callback=self._shed_fallback_row(cb),
                trace=ref,
            )

    def _on_datapoint(self, ident: bytes) -> None:
        pass  # segment emission happens in _on_message

    def _on_episode_over(self, ident: bytes) -> None:
        client = self.clients[ident]
        if self.score_queue is not None:
            try:
                self.score_queue.put_nowait(client.score)
            except queue.Full:
                pass
        client.score = 0.0

    def _on_message(self, ident: bytes, state, reward: float, is_over: bool) -> None:
        """Attach (reward, done) to the newest transition, emit full unrolls,
        then request the next action.

        Runs ONLY in the master thread, and the emission check happens before
        ``_on_state`` queues the next predict task — so no predictor-thread
        append can race the ``client.memory`` reslice (the simulator is
        blocked on its action until the callback runs).
        """
        client = self.clients[ident]
        if len(client.memory) > 0:
            step = client.memory[-1]
            step.reward = self._learn_reward(reward)
            step.done = is_over
            client.score += reward  # scores stay RAW
            if is_over:
                self._on_episode_over(ident)
            self._maybe_emit(ident)
        self._on_state(state, ident)

    def _maybe_emit(self, ident: bytes) -> None:
        """When T+1 completed transitions exist, emit the first T.

        The (T+1)-th transition's state is the bootstrap state AND the first
        transition of the next segment — unrolls tile time with no gaps.
        """
        client = self.clients[ident]
        T = self.unroll_len
        if len(client.memory) < T + 1:
            return
        seg, rest = client.memory[:T], client.memory[T:]
        segment = {
            # per-env compat foil: these states are per-simulator arrays
            # (no ring window to defer into), so the stack stays — the
            # staged collate still writes them once into the slot
            "state": np.stack([s.state for s in seg]),  # ba3clint: disable=A13
            "action": np.asarray([s.action for s in seg], np.int32),
            "reward": np.asarray([s.reward for s in seg], np.float32),
            "done": np.asarray([s.done for s in seg], np.float32),
            "behavior_log_probs": np.asarray([s.logp for s in seg], np.float32),
            "bootstrap_state": rest[0].state,
        }
        if self.record_values:
            segment["behavior_values"] = np.asarray(
                [s.value for s in seg], np.float32
            )
        # a sampled step inside this unroll hands its trace to the segment
        # (claimed once; stripped by the feed before collate)
        for s in seg:
            if s.trace is not None:
                segment["_trace"] = s.trace.hop("unroll_flush", self.tele_role)
                s.trace = None
                break
        client.memory = rest
        # backpressure pauses actors, but must stay shutdown-responsive
        self._put_stoppable(self.queue, segment)
        self._c_datapoints.inc(T)

    # -- block wire (one message per env-server per step) ------------------
    def _on_block_state(self, states: np.ndarray, ident: bytes) -> None:
        blk = self.clients[ident]
        # claim the receive loop's parked trace ref (tracing.py sampling)
        ref, blk.pending_trace = blk.pending_trace, None
        if ref is not None:
            # receive -> dispatch stays a MASTER hop, never inside the
            # predict spans (BA3CSimulatorMaster._on_state documents why)
            ref = ref.hop("master_ingest", self.tele_role)

        def cb(actions: np.ndarray, values: np.ndarray, logps: np.ndarray):
            # safe cross-thread append: the env server is blocked awaiting
            # this very action block, so the master cannot reslice blk.steps
            # until send_block_actions below releases it (protocol
            # serialization, same argument as the per-env callback)
            st = BlockStep(states, actions, values, logps)
            if ref is not None:
                # serve RTT (recv -> actions); the predictor's own
                # dispatch/fetch sub-spans ride the same trace
                st.trace = ref.hop("predict", self.tele_role)
            blk.steps.append(st)
            self.send_block_actions(ident, actions)

        if ref is None:
            self.predictor.put_block_task(
                states, cb,
                shed_callback=self._shed_fallback_block(cb, len(states)),
            )
        else:
            self.predictor.put_block_task(
                states, cb,
                shed_callback=self._shed_fallback_block(cb, len(states)),
                trace=ref,
            )

    def _on_block_flush(self, ident: bytes) -> None:
        """Per-env unroll emission (block analogue of :meth:`_maybe_emit`).

        Unrolls run straight across episode boundaries, so in block mode
        every env emits at the same lockstep tick — but the loop stays
        per-env and pointer-driven (``blk.start``) so the semantics hold
        even if a subclass ever desynchronizes envs.
        """
        blk: BlockClientState = self.clients[ident]
        T = self.unroll_len
        t_end = len(blk.steps)
        # hoisted trace arm check: the per-segment trace scan runs only
        # when sampling is live, so the tracing-off hot path pays ONE call
        # per flush tick (the --trace both gate's off arm)
        trace_on = tracing.enabled()
        for j in range(blk.n_envs):
            while t_end - blk.start[j] >= T + 1:
                s = int(blk.start[j])
                seg = blk.steps[s : s + T]
                segment = {
                    # LAZY env column (SegStates): the flush no longer
                    # pays a full obs copy per segment — the bytes cross
                    # the host exactly once, at the (staged) collate
                    "state": SegStates([st.states for st in seg], j),
                    "action": np.asarray(
                        [st.actions[j] for st in seg], np.int32
                    ),
                    "reward": np.asarray(
                        [st.rewards[j] for st in seg], np.float32
                    ),
                    "done": np.asarray(
                        [st.dones[j] for st in seg], np.float32
                    ),
                    "behavior_log_probs": np.asarray(
                        [st.logps[j] for st in seg], np.float32
                    ),
                    # the (T+1)-th step's state: bootstrap AND next head
                    "bootstrap_state": blk.steps[s + T].states[j],
                }
                if self.record_values:
                    # BlockStep already carries the served values — the
                    # V-trace plane just never emits them
                    segment["behavior_values"] = np.asarray(
                        [st.values[j] for st in seg], np.float32
                    )
                if trace_on:
                    # a sampled step's trace continues on the FIRST
                    # segment that flushes it (one block lifetime = one
                    # trace; the other B-1 envs share the step object,
                    # claimed once)
                    for st in seg:
                        if st.trace is not None:
                            segment["_trace"] = st.trace.hop(
                                "unroll_flush", self.tele_role
                            )
                            st.trace = None
                            break
                blk.start[j] = s + T
                self._put_stoppable(self.queue, segment)
                # batched telemetry per emitted segment (T datapoints, one
                # inc) + e2e latency of the segment's head step (recv_t is
                # 0.0 with telemetry disabled — skip the monotonic math)
                self._c_datapoints.inc(T)
                if seg[0].recv_t:
                    self._h_ingest.observe(time.monotonic() - seg[0].recv_t)
        self._drop_flushed_prefix(blk)
