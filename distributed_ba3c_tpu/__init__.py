"""distributed_ba3c_tpu — a TPU-native rebuild of Distributed-BA3C.

A from-scratch JAX/XLA/Pallas framework with the capabilities of
AdamStelmaszczyk/Distributed-BA3C (Tensorpack-vintage distributed A3C for Atari,
arXiv:1801.02852), re-designed TPU-first:

- The reference's TF parameter-server gradient plane (async grad push over gRPC,
  ``src/train.py`` + ``tensorpack/train/multigpu.py``; SURVEY.md §2.5) becomes a
  single jitted synchronous update with ``lax.psum`` over an ICI device mesh
  (:mod:`distributed_ba3c_tpu.parallel`).
- The reference's experience plane (``tensorpack/RL/simulator.py`` ZMQ actors;
  SURVEY.md §2.3) is kept shape-compatible: ``SimulatorProcess``/``SimulatorMaster``
  over ZMQ + msgpack (:mod:`distributed_ba3c_tpu.rl.simulator`).
- The reference's ``MultiThreadAsyncPredictor`` micro-batching inference
  (``tensorpack/predict/concurrency.py``; SURVEY.md §2.3 #10) becomes one vmap'd,
  jitted forward + on-device action sampling feeding thousands of simulators
  (:mod:`distributed_ba3c_tpu.predict`).

NOTE: the reference mount (/root/reference) was EMPTY at build time; reference
citations throughout this package use the *expected path* convention defined in
SURVEY.md §0 (Tensorpack-vintage layout, confidence-tagged).
"""

from distributed_ba3c_tpu.version import __version__

__all__ = ["__version__"]
