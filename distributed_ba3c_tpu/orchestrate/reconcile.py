"""One generic reconcile loop over the whole topology (docs/topology.md).

Every controller this repo grew — FleetSupervisor, Autoscaler,
LearnerSupervisor, PodSupervisor, ReplicaSet/ReplicaAutoscaler/
PromotionController — is the same loop wearing a different idiom: watch
the live state, compare against the desired state, act through a
factory. This module is that loop ONCE: resources implement the
:class:`Reconcilable` protocol (observe → diff → act → retire) as thin
adapters over the EXISTING machinery (FleetSupervisor slots,
PodSupervisor hosts, ReplicaSet incarnations, the LearnerSupervisor
resume gate), and one :class:`Reconciler` thread ticks them all:

- **observe** returns a plain-dict snapshot of the live state (process
  table, the masters'/router's own health accounts, telemetry);
- **diff** is a PURE function of that snapshot — desired vs live → the
  exact action list (the deterministic unit suite in
  tests/test_reconcile.py pins it);
- **act** executes one action through the existing factories, under a
  per-resource exponential backoff (a failing respawn retries next tick,
  later and later) and a topology-wide restart-budget circuit breaker
  (a crash loop anywhere degrades to a visible incident, never a fork
  storm);
- every decision is flight-recorded WITH its input snapshot, so the
  postmortem shows what the loop saw when it acted.

Telemetry lands under the ``reconciler`` role (docs/observability.md):
``reconcile_actions_total``, ``reconcile_drift_gauge``, per-resource
heal counters, circuit state.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import threading
import time
import weakref
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from distributed_ba3c_tpu import telemetry
from distributed_ba3c_tpu.orchestrate.topology import ReconcilePolicy
from distributed_ba3c_tpu.utils import logger
from distributed_ba3c_tpu.utils.concurrency import StoppableThread

#: resource kinds with a dedicated heal counter (literal names so the
#: ba3cwire W5 catalog check sees every series; an unknown kind falls
#: back to the generic action counter only)
HEAL_KINDS = ("fleet", "pod", "learner", "serving")

#: verbs that count against the restart budget — healing state changes,
#: as opposed to policy evaluations ("tick") which are free
HEAL_VERBS = ("spawn", "respawn", "kill", "replace", "re-arm", "scale")


@dataclasses.dataclass(frozen=True)
class Action:
    """One reconcile decision: what to do to which resource, and why."""

    verb: str
    resource: str
    reason: str = ""
    detail: Tuple[Tuple[str, Any], ...] = ()

    @classmethod
    def make(cls, verb: str, resource: str, reason: str = "", **detail):
        return cls(
            verb=verb, resource=resource, reason=reason,
            detail=tuple(sorted(detail.items())),
        )

    def detail_dict(self) -> Dict[str, Any]:
        return dict(self.detail)


class Reconcilable:
    """The one controller protocol (duck-typed; this base documents it).

    ``kind`` buckets the resource's heal counter (``fleet``/``pod``/
    ``learner``/``serving``/``policy``); ``name`` is its identity in
    actions and flight events. ``observe()`` must not mutate; ``diff``
    must be pure in the observation; ``act`` performs exactly one
    action's worth of work through the existing factories; ``retire``
    releases everything (idempotent).
    """

    kind: str = ""
    name: str = ""

    def prepare(self) -> None:
        """Bring up the initial desired state (called from
        Reconciler.start, before the loop runs)."""

    def observe(self) -> Dict[str, Any]:
        raise NotImplementedError

    def diff(self, observed: Dict[str, Any]) -> List[Action]:
        raise NotImplementedError

    def act(self, action: Action) -> None:
        raise NotImplementedError

    def retire(self) -> None:
        """Release the resource (teardown; idempotent)."""


# --------------------------------------------------------------------------
# the pure diff functions (the deterministic unit suite's surface)
# --------------------------------------------------------------------------

def diff_fleet(name: str, obs: Dict[str, Any]) -> List[Action]:
    """Desired vs live for a supervised fleet (env servers or pod hosts).

    Order is the supervisor's own: wedged slots die first (they hold wire
    identities), then due vacancies respawn; backoff-parked vacancies are
    DRIFT but not actions (their retry time has not come). A supervisor
    whose own circuit is open parks everything except wedge kills.
    """
    out: List[Action] = []
    for ident in obs.get("wedged", ()):
        out.append(Action.make(
            "kill", name, reason="wedged (alive but pruned)", ident=ident,
        ))
    if obs.get("circuit_open"):
        return out
    for idx in obs.get("vacant_due", ()):
        verb = "spawn" if not obs.get("ever_started", True) else "respawn"
        out.append(Action.make(
            verb, name, reason="slot vacant and due", slot=idx,
        ))
    delta = int(obs.get("scale_delta", 0))
    if delta:
        out.append(Action.make(
            "scale", name,
            reason=str(obs.get("scale_reason", "autoscale")),
            delta=delta,
        ))
    return out


def diff_learner(name: str, obs: Dict[str, Any]) -> List[Action]:
    """The learner's failover state machine, as a diff.

    done/given-up topologies want nothing; a stalled attempt is killed
    (the resume path takes over next tick); a dead-or-never-started
    learner re-arms through the resume gate — ``--load`` exactly when a
    finalized checkpoint exists.
    """
    if obs.get("done") or obs.get("given_up"):
        return []
    if obs.get("running"):
        if obs.get("stalled"):
            return [Action.make(
                "kill", name, reason="stall watchdog",
                attempt=obs.get("attempt"),
            )]
        return []
    return [Action.make(
        "re-arm", name,
        reason=(
            "resume from finalized checkpoint"
            if obs.get("finalized_step") is not None
            else "start from scratch (no finalized checkpoint)"
        ),
        attempt=obs.get("attempt"),
        resume_step=obs.get("finalized_step"),
    )]


def diff_serving(name: str, obs: Dict[str, Any]) -> List[Action]:
    """Dead replicas are replaced 1:1 (heal-to-count rides the same
    act), and a set short of its floor grows back."""
    out: List[Action] = []
    for rid in obs.get("dead", ()):
        out.append(Action.make(
            "replace", name, reason="router declared replica dead",
            replica=rid,
        ))
    shortfall = int(obs.get("min_replicas", 1)) - int(obs.get("target", 0))
    if shortfall > 0 and not obs.get("dead"):
        out.append(Action.make(
            "spawn", name, reason="replica set below floor", n=shortfall,
        ))
    return out


# --------------------------------------------------------------------------
# resource adapters over the existing controllers
# --------------------------------------------------------------------------

class FleetResource(Reconcilable):
    """FleetSupervisor (or PodSupervisor — same slot machinery, whole
    host groups) as a Reconcilable. The supervisor thread is NOT started;
    the reconciler owns the tick. One underlying ``tick()`` call heals
    every action in a round (the supervisor's slot pass is atomic by
    design), so acts after the first in a round are satisfied no-ops.
    """

    def __init__(self, name: str, supervisor, kind: str = "fleet",
                 scale_intent: Optional[Callable[[], Tuple[int, str]]] = None):
        self.kind = kind
        self.name = name
        self.supervisor = supervisor
        # optional () -> (delta, reason) hook for an external scale
        # driver (tests, the bench); the production autoscalers ride as
        # PolicyResources and call scale_by through their own tick
        self.scale_intent = scale_intent
        self._ticked_in_round = False

    def prepare(self) -> None:
        self.supervisor.spawn_initial()

    def observe(self) -> Dict[str, Any]:
        self._ticked_in_round = False
        obs = self.supervisor.observe()
        if self.scale_intent is not None:
            delta, reason = self.scale_intent()
            if delta:
                obs["scale_delta"] = delta
                obs["scale_reason"] = reason
        return obs

    def diff(self, observed: Dict[str, Any]) -> List[Action]:
        return diff_fleet(self.name, observed)

    def act(self, action: Action) -> None:
        if action.verb == "scale":
            self.supervisor.scale_by(
                int(action.detail_dict()["delta"]), reason=action.reason
            )
            return
        if not self._ticked_in_round:
            self._ticked_in_round = True
            self.supervisor.tick()

    def retire(self) -> None:
        self.supervisor.close()


class LearnerResource(Reconcilable):
    """LearnerSupervisor's resume gate, reconciler-ticked: the attempt
    runs as a non-blocking child; death re-arms through the finalized-
    checkpoint gate with the SAME accounting as the blocking loop."""

    kind = "learner"

    def __init__(self, name: str, supervisor):
        self.name = name
        self.supervisor = supervisor
        self._done = False
        self._given_up = False
        self._final_rc: Optional[int] = None

    @property
    def final_rc(self) -> Optional[int]:
        """0 once the learner finished cleanly; the fatal rc after a
        give-up; None while supervision is still live."""
        return self._final_rc

    def observe(self) -> Dict[str, Any]:
        sup = self.supervisor
        from distributed_ba3c_tpu.orchestrate.learner import finalized_step

        return {
            "kind": "learner",
            "running": sup.attempt_running(),
            "stalled": sup.attempt_stalled(),
            "attempt": sup.attempt,
            "finalized_step": finalized_step(sup.ckpt_dir),
            "done": self._done,
            "given_up": self._given_up,
        }

    def diff(self, observed: Dict[str, Any]) -> List[Action]:
        return diff_learner(self.name, observed)

    def act(self, action: Action) -> None:
        sup = self.supervisor
        if action.verb == "kill":
            sup.kill_attempt(reason="stall")
            return
        # re-arm: account the previous attempt's death (if any), then
        # relaunch through the resume gate — unless the budget is spent
        rc = sup.reap_attempt()
        if rc is not None:
            verdict = sup.note_exit(rc)
            if verdict == "done":
                self._done, self._final_rc = True, 0
                return
            if verdict == "giveup":
                self._given_up, self._final_rc = True, rc
                return
        sup.start_attempt()

    def retire(self) -> None:
        self.supervisor.terminate_attempt()


class ServingResource(Reconcilable):
    """ReplicaSet incarnations as a Reconcilable: the set's own corpse-
    sweeper thread is NOT started (``ReplicaSet.start(n,
    reconcile_thread=False)``); the router's health verdicts drive the
    diff and ``ReplicaSet.reconcile()`` is the act."""

    kind = "serving"

    def __init__(self, name: str, replica_set):
        self.name = name
        self.replica_set = replica_set
        self._healed_in_round = False

    def observe(self) -> Dict[str, Any]:
        self._healed_in_round = False
        rs = self.replica_set
        states = rs.router.replica_states()
        live = rs.replica_ids()
        return {
            "kind": "serving",
            "target": len(live),
            "min_replicas": rs.min_replicas,
            "max_replicas": rs.max_replicas,
            "dead": tuple(r for r in live if states.get(r) == "dead"),
            "states": dict(states),
        }

    def diff(self, observed: Dict[str, Any]) -> List[Action]:
        return diff_serving(self.name, observed)

    def act(self, action: Action) -> None:
        if self._healed_in_round:
            return
        self._healed_in_round = True
        if action.verb == "spawn":
            self.replica_set.scale_to(
                self.replica_set.min_replicas, reason=action.reason
            )
        else:
            self.replica_set.reconcile()

    def retire(self) -> None:
        # the router owns the set's close in cli.py (router.replica_set);
        # a bench-owned set retires here
        pass


class PolicyResource(Reconcilable):
    """A periodic control loop (ReplicaAutoscaler, PromotionController —
    anything with ``tick()``) ridden by the reconciler at its own
    interval. Policy evaluations are counted, not flight-spammed: the
    policies flight-record their own decisions."""

    kind = "policy"

    def __init__(self, name: str, controller, interval_s: float = 2.0):
        self.name = name
        self.controller = controller
        self.interval_s = max(0.0, float(interval_s))
        self._last_tick = 0.0

    def observe(self) -> Dict[str, Any]:
        return {"kind": "policy", "due": (
            time.monotonic() - self._last_tick >= self.interval_s
        )}

    def diff(self, observed: Dict[str, Any]) -> List[Action]:
        if observed.get("due"):
            return [Action.make("tick", self.name, reason="interval elapsed")]
        return []

    def act(self, action: Action) -> None:
        self._last_tick = time.monotonic()
        self.controller.tick()

    def retire(self) -> None:
        stop = getattr(self.controller, "stop", None)
        if stop is not None:
            try:
                stop()
            except Exception:
                pass


# --------------------------------------------------------------------------
# the loop
# --------------------------------------------------------------------------

class _ResourceState:
    __slots__ = ("failures", "next_act_t")

    def __init__(self):
        self.failures = 0
        self.next_act_t = 0.0


class Reconciler(StoppableThread):
    """One loop, every resource: observe → diff → act, per-resource
    exponential backoff, topology-wide circuit breaker, every decision
    flight-recorded with its input snapshot.

    Satisfies the StartProcOrThread protocol (start/stop/join/close), so
    cli.py appends ONE startable where five controllers used to ride.
    ``tick_once()`` is public: tests and the bench drive the loop
    deterministically without the thread.
    """

    def __init__(
        self,
        policy: Optional[ReconcilePolicy] = None,
        resources: Iterable[Reconcilable] = (),
        tele_role: str = "reconciler",
    ):
        super().__init__(daemon=True, name="Reconciler")
        self.policy = policy or ReconcilePolicy()
        self._resources: List[Reconcilable] = []
        self._state: Dict[str, _ResourceState] = {}
        self._lock = threading.Lock()
        self._heal_times: collections.deque = collections.deque()
        self._circuit_open = self.policy.restart_budget == 0
        self._flight = telemetry.flight_recorder()
        tele = telemetry.registry(tele_role)
        self._c_ticks = tele.counter("reconcile_ticks_total")
        self._c_actions = tele.counter("reconcile_actions_total")
        self._c_policy = tele.counter("reconcile_policy_ticks_total")
        self._c_errors = tele.counter("reconcile_errors_total")
        self._c_skipped = tele.counter("reconcile_skipped_total")
        self._c_trips = tele.counter("reconcile_circuit_trips_total")
        self._c_heal = {
            "fleet": tele.counter("reconcile_heal_fleet_total"),
            "pod": tele.counter("reconcile_heal_pod_total"),
            "learner": tele.counter("reconcile_heal_learner_total"),
            "serving": tele.counter("reconcile_heal_serving_total"),
        }
        self._g_drift = tele.gauge("reconcile_drift_gauge")
        ref = weakref.ref(self)
        tele.gauge(
            "reconcile_circuit_open",
            fn=lambda: int(s._circuit_open) if (s := ref()) else 0,
        )
        for r in resources:
            self.add(r)

    # -- assembly ----------------------------------------------------------
    def add(self, resource: Reconcilable) -> Reconcilable:
        if not resource.name:
            raise ValueError("a Reconcilable needs a name")
        with self._lock:
            if any(r.name == resource.name for r in self._resources):
                raise ValueError(f"duplicate resource name {resource.name!r}")
            self._resources.append(resource)
            self._state[resource.name] = _ResourceState()
        return resource

    def resources(self) -> List[Reconcilable]:
        with self._lock:
            return list(self._resources)

    @property
    def circuit_open(self) -> bool:
        return self._circuit_open

    # -- lifecycle (StartProcOrThread protocol) ----------------------------
    def start(self) -> None:
        for r in self.resources():
            r.prepare()
        super().start()
        logger.info(
            "reconciler up: %d resources (%s), budget %d/%gs",
            len(self._resources),
            ", ".join(f"{r.kind}:{r.name}" for r in self.resources()),
            self.policy.restart_budget, self.policy.budget_window_s,
        )

    def run(self) -> None:
        while not self.stopped():
            try:
                self.tick_once()
            except Exception:
                # the reconcile loop is the component that must not die
                # of one bad tick — log and keep reconciling
                logger.exception("reconcile tick failed")
            self._stop_evt.wait(self.policy.poll_interval_s)

    def join(self, timeout: Optional[float] = None) -> None:
        if self.is_alive():
            super().join(timeout)

    def close(self) -> None:
        self.stop()
        self.join(timeout=5)
        # retire in reverse add order: serving/policies before the fleets
        # their traffic rides on is the caller's ordering to choose; the
        # guarantee here is every retire runs even when one raises
        for r in reversed(self.resources()):
            try:
                r.retire()
            except Exception:
                logger.exception("retire of %s failed", r.name)

    # -- the tick ----------------------------------------------------------
    def tick_once(self) -> List[Action]:
        """One full observe→diff→act pass over every resource; returns
        the actions EXECUTED (skips and backoff parks excluded)."""
        now = time.monotonic()
        self._c_ticks.inc()
        executed: List[Action] = []
        drift = 0
        for res in self.resources():
            st = self._state[res.name]
            try:
                obs = res.observe()
                actions = res.diff(obs)
            except Exception:
                self._c_errors.inc()
                logger.exception("observe/diff of %s failed", res.name)
                continue
            heal_actions = [a for a in actions if a.verb != "tick"]
            drift += len(heal_actions)
            if heal_actions and now < st.next_act_t:
                # this resource's last act failed: it is parked under
                # exponential backoff, its drift stays on the gauge
                self._c_skipped.inc()
                continue
            for action in actions:
                healing = action.verb != "tick"
                if healing and self._circuit_open:
                    self._c_skipped.inc()
                    continue
                try:
                    res.act(action)
                except Exception as e:
                    st.failures += 1
                    st.next_act_t = now + self.policy.backoff_s(st.failures)
                    self._c_errors.inc()
                    self._flight.record(
                        "reconcile_act_error",
                        resource=res.name, verb=action.verb,
                        error=repr(e)[:200], failures=st.failures,
                        retry_in_s=round(st.next_act_t - now, 2),
                    )
                    logger.exception(
                        "act %s on %s failed (failure #%d, retry in %.1fs)",
                        action.verb, res.name, st.failures,
                        st.next_act_t - now,
                    )
                    break  # park the resource; later actions wait too
                else:
                    if healing:
                        st.failures = 0
                        st.next_act_t = 0.0
                        self._c_actions.inc()
                        if res.kind in self._c_heal:
                            self._c_heal[res.kind].inc()
                        if action.verb in HEAL_VERBS:
                            self._heal_times.append(time.monotonic())
                        # the decision AND what the loop saw when it made
                        # it — the postmortem is the artifact
                        self._flight.record(
                            "reconcile_action",
                            resource=res.name, resource_kind=res.kind,
                            verb=action.verb, reason=action.reason,
                            detail=action.detail_dict(),
                            snapshot=_json_safe(obs),
                        )
                        executed.append(action)
                    else:
                        self._c_policy.inc()
        self._update_circuit(time.monotonic())
        self._g_drift.set(drift)
        return executed

    def _update_circuit(self, now: float) -> None:
        """FleetSpec's breaker shape, topology-wide: open past the
        budget, half-close when the window drains to half of it."""
        budget = self.policy.restart_budget
        window = self.policy.budget_window_s
        while self._heal_times and now - self._heal_times[0] > window:
            self._heal_times.popleft()
        if budget == 0:
            return
        if not self._circuit_open and len(self._heal_times) > budget:
            self._circuit_open = True
            self._c_trips.inc()
            self._flight.record(
                "reconcile_circuit_open",
                heals_in_window=len(self._heal_times), budget=budget,
            )
            logger.error(
                "reconcile circuit OPEN: %d heal actions in %.0fs "
                "(budget %d) — healing paused until the window drains",
                len(self._heal_times), window, budget,
            )
        elif self._circuit_open and len(self._heal_times) <= budget // 2:
            self._circuit_open = False
            self._flight.record(
                "reconcile_circuit_close",
                heals_in_window=len(self._heal_times),
            )
            logger.info("reconcile circuit closed (half-open drain)")


def _json_safe(obj: Any, depth: int = 4) -> Any:
    """Snapshots ride the flight ring and the bench artifact: clamp them
    to JSON-able plain data so one exotic observation cannot poison the
    postmortem dump."""
    if depth <= 0:
        return repr(obj)[:80]
    if isinstance(obj, dict):
        return {
            str(k)[:80]: _json_safe(v, depth - 1)
            for k, v in list(obj.items())[:32]
        }
    if isinstance(obj, (list, tuple)):
        return [_json_safe(v, depth - 1) for v in list(obj)[:32]]
    if isinstance(obj, (str, int, float, bool)) or obj is None:
        return obj
    try:
        json.dumps(obj)
        return obj
    except (TypeError, ValueError):
        return repr(obj)[:80]
