"""Declarative fleet spec: what the supervisor owns and how it may act.

One frozen dataclass describes a fleet end-to-end — the env-server shape
(game, wire, envs per server, pipes) plus the ORCHESTRATION policy (size
bounds, respawn backoff, restart budget). The supervisor
(orchestrate/supervisor.py) is pure mechanism; every number it acts on
lives here, so a fleet's behavior is reviewable as data and a spec file
checked into a run's logdir reproduces its orchestration exactly.

The reference paper's 64-node cluster had no equivalent: fleet shape was
an ssh fan-out argument and policy was an operator reading logs
(SURVEY.md §2.8 #29). docs/orchestration.md documents every knob.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Optional


@dataclasses.dataclass(frozen=True)
class FleetSpec:
    """A supervised env-server fleet, sized in SERVER PROCESSES.

    ``fleet_size`` is the launch target; the autoscaler (when attached)
    moves the target inside ``[fleet_min, fleet_max]``. Each server hosts
    ``envs_per_server`` lockstep envs, so total env count scales in
    server-sized steps — the granularity the wire already batches at.
    """

    # -- env-server shape (mirrors CppEnvServerProcess's surface) ---------
    pipe_c2s: str = ""
    pipe_s2c: str = ""
    game: str = "pong"
    envs_per_server: int = 16
    frame_history: int = 4
    wire: str = "block"
    shm_ring_cap: Optional[int] = None
    #: first server index — distinct across actor hosts so ZMQ identities
    #: (cppsim-<idx>...) never collide (scripts/launch_env_fleet.py)
    base_idx: int = 0

    # -- fleet sizing ------------------------------------------------------
    fleet_size: int = 4
    fleet_min: int = 1
    fleet_max: int = 8

    # -- respawn policy ----------------------------------------------------
    #: first-respawn delay; doubles per consecutive failure of the slot
    backoff_base_s: float = 0.5
    backoff_max_s: float = 30.0
    #: a slot alive this long resets its consecutive-failure streak
    stable_after_s: float = 30.0
    #: circuit breaker: more than this many respawns inside
    #: ``budget_window_s`` opens the circuit (respawns pause fleet-wide
    #: until the window drains to half the budget) — a crash LOOP must
    #: degrade to a visible incident, not an infinite fork storm
    restart_budget: int = 16
    budget_window_s: float = 300.0

    def __post_init__(self):
        if self.wire not in ("block-shm", "block", "per-env"):
            raise ValueError(f"unknown wire {self.wire!r}")
        if self.envs_per_server < 1:
            raise ValueError("envs_per_server must be >= 1")
        if not (1 <= self.fleet_min <= self.fleet_max):
            raise ValueError(
                f"need 1 <= fleet_min <= fleet_max, got "
                f"[{self.fleet_min}, {self.fleet_max}]"
            )
        if not (self.fleet_min <= self.fleet_size <= self.fleet_max):
            raise ValueError(
                f"fleet_size {self.fleet_size} outside "
                f"[{self.fleet_min}, {self.fleet_max}]"
            )
        if self.backoff_base_s < 0 or self.backoff_max_s < self.backoff_base_s:
            raise ValueError(
                f"need 0 <= backoff_base_s <= backoff_max_s, got "
                f"{self.backoff_base_s}/{self.backoff_max_s}"
            )
        if self.restart_budget < 0:
            raise ValueError("restart_budget must be >= 0")

    def backoff_s(self, consecutive_failures: int) -> float:
        """Respawn delay after the N-th consecutive failure of one slot
        (N >= 1): ``base * 2^(N-1)`` capped at ``backoff_max_s``."""
        n = max(1, int(consecutive_failures))
        return min(self.backoff_max_s, self.backoff_base_s * (2 ** (n - 1)))

    # -- (de)serialization -------------------------------------------------
    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), indent=2, sort_keys=True)

    @classmethod
    def from_json(cls, text: str) -> "FleetSpec":
        doc = json.loads(text)
        if not isinstance(doc, dict):
            raise ValueError("fleet spec must be a JSON object")
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = sorted(set(doc) - known)
        if unknown:
            # a typoed knob must fail the launch, not silently run with
            # the default it was trying to override
            raise ValueError(f"unknown fleet spec fields: {unknown}")
        return cls(**doc)

    @classmethod
    def load(cls, path: str) -> "FleetSpec":
        with open(path) as fh:
            return cls.from_json(fh.read())
