"""TopologySpec: the WHOLE deployment as one declarative document.

FleetSpec (orchestrate/spec.py) made one fleet reviewable as data; this
module extends the same frozen, JSON-round-tripping, unknown-field-
rejecting pattern to the full topology — fleets, pod hosts, the learner,
serving replicas, SLO/staleness bounds, and the chaos/netchaos schedules
— so a deployment is ONE document the reconcile loop
(orchestrate/reconcile.py) heals toward, and a topology change is a spec
edit, not a cli.py rewiring (ROADMAP item 5, docs/topology.md).

Validation is the spec's job, not the flag parser's: every half-specified
combo cli.py used to police inline (a canary without a load, serving
flags on the fused trainer, fleet bounds around an external fleet) is a
:class:`TopologyError` raised at construction, which both entry points
(cli.py, ``python -m distributed_ba3c_tpu.orchestrate --topology``)
convert to a clean exit-2 usage error — junk, truncated or type-confused
JSON must never escape as a raw traceback (the fuzz suite in
tests/test_topology.py pins this).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, Mapping, Optional, Tuple

from distributed_ba3c_tpu.orchestrate.spec import FleetSpec


class TopologyError(ValueError):
    """A spec that names an impossible deployment (usage error, exit 2)."""


def _dataclass_from_doc(cls, doc: Any, where: str):
    """The FleetSpec unknown-field contract, applied at every nesting
    level: a typoed knob fails the launch, never silently runs with the
    default it was trying to override."""
    if not isinstance(doc, Mapping):
        raise TopologyError(
            f"{where} must be a JSON object, got {type(doc).__name__}"
        )
    known = {f.name for f in dataclasses.fields(cls)}
    unknown = sorted(set(doc) - known)
    if unknown:
        raise TopologyError(f"unknown {where} fields: {unknown}")
    try:
        return cls(**doc)
    except TopologyError:
        raise
    except (TypeError, ValueError) as e:
        raise TopologyError(f"bad {where}: {e}") from None


@dataclasses.dataclass(frozen=True)
class LearnerTopology:
    """The supervised learner: train.py args + the resume/watchdog policy
    (orchestrate/learner.py LearnerSupervisor's surface)."""

    logdir: str = ""
    #: train.py argv (must include a matching --logdir, never --load —
    #: the resume gate owns --load; LearnerSupervisor validates)
    train_args: Tuple[str, ...] = ()
    max_restarts: int = 5
    stall_secs: float = 0.0
    startup_grace_s: float = 600.0
    poll_s: float = 1.0

    def __post_init__(self):
        object.__setattr__(self, "train_args", tuple(
            str(a) for a in self.train_args
        ))
        if not self.logdir:
            raise TopologyError("learner.logdir must be set")
        if self.max_restarts < 0:
            raise TopologyError("learner.max_restarts must be >= 0")
        if self.stall_secs < 0 or self.startup_grace_s < 0:
            raise TopologyError("learner stall/grace must be >= 0")


@dataclasses.dataclass(frozen=True)
class PodTopology:
    """A pod of whole actor hosts (orchestrate/pod.py PodSupervisor) and
    the learner-side staleness bound the pod plane gates on."""

    hosts: int = 2
    sims_per_host: int = 2
    pipe_c2s: str = ""
    pipe_s2c: str = ""
    env: str = "fake"
    #: bounded-staleness gate (docs/pod.md): -1 = unbounded
    max_staleness: int = -1
    restart_budget: int = 16
    budget_window_s: float = 300.0
    backoff_base_s: float = 0.5
    backoff_max_s: float = 30.0

    def __post_init__(self):
        if self.hosts < 1:
            raise TopologyError(f"pod.hosts must be >= 1, got {self.hosts}")
        if self.sims_per_host < 1:
            raise TopologyError("pod.sims_per_host must be >= 1")
        if self.max_staleness < -1:
            raise TopologyError(
                "pod.max_staleness is a version lag (-1 = unbounded), got "
                f"{self.max_staleness}"
            )
        if self.restart_budget < 0:
            raise TopologyError("pod.restart_budget must be >= 0")


@dataclasses.dataclass(frozen=True)
class ServingTopology:
    """The serving tier: replica count/bounds behind the SLO router, plus
    the canary/shadow policy table (docs/serving.md)."""

    replicas: int = 1
    replicas_max: int = 0  # 0 = fixed count (no autoscaler)
    slo_ms: float = 0.0
    canary_load: str = ""
    canary_fraction: float = 0.0
    canary_autopromote: bool = False
    shadow_load: str = ""
    autoscale_interval_s: float = 5.0

    def __post_init__(self):
        if self.replicas < 1:
            raise TopologyError(
                f"serving.replicas must be >= 1, got {self.replicas}"
            )
        if self.replicas_max:
            if self.replicas_max < self.replicas:
                raise TopologyError(
                    f"serving.replicas_max {self.replicas_max} < "
                    f"serving.replicas {self.replicas}"
                )
            if not self.slo_ms:
                raise TopologyError(
                    "serving.replicas_max autoscales on the serving SLO — "
                    "it requires serving.slo_ms (the watermark is "
                    "served-p99 against that budget)"
                )
        if self.canary_autopromote:
            if not self.canary_load:
                raise TopologyError(
                    "serving.canary_autopromote needs serving.canary_load "
                    "(the candidate checkpoint to canary)"
                )
            if self.replicas < 2 or not self.slo_ms:
                raise TopologyError(
                    "serving.canary_autopromote runs on the serving ROUTER "
                    "— it requires serving.replicas >= 2 and "
                    "serving.slo_ms (the breach budget)"
                )
        if bool(self.canary_load) != bool(self.canary_fraction > 0):
            raise TopologyError(
                "serving.canary_load and serving.canary_fraction come "
                "together: the checkpoint names WHAT to canary, the "
                "fraction names HOW MUCH traffic it gets"
            )
        if not 0 <= self.canary_fraction <= 1:
            raise TopologyError(
                "serving.canary_fraction must be a traffic fraction in "
                f"[0, 1], got {self.canary_fraction}"
            )

    @property
    def routed(self) -> bool:
        """True when the plane needs the router (R > 1, or autoscale
        headroom above a single replica)."""
        return self.replicas > 1 or bool(
            self.replicas_max and self.replicas_max > self.replicas
        )


@dataclasses.dataclass(frozen=True)
class ChaosTopology:
    """A seeded ChaosMonkey schedule (orchestrate/chaos.py) — present in
    the spec so a certification run's kill cadence is part of the
    document it certifies."""

    seed: int = 0
    interval_s: float = 5.0
    jitter_s: float = 0.0
    max_kills: int = 0  # 0 = unbounded
    initial_delay_s: float = 0.0

    def __post_init__(self):
        if self.interval_s <= 0:
            raise TopologyError("chaos.interval_s must be > 0")
        if self.max_kills < 0 or self.jitter_s < 0 or self.initial_delay_s < 0:
            raise TopologyError("chaos bounds must be >= 0")


@dataclasses.dataclass(frozen=True)
class NetChaosTopology:
    """A netchaos FaultSchedule document (netchaos/schedule.py JSON form:
    per-link faults + partition windows under one seed)."""

    seed: int = 0
    links: Mapping[str, Any] = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        # delegate link/partition validation to the schedule itself so the
        # two JSON forms cannot drift; keep the plain dict for round-trip
        from distributed_ba3c_tpu.netchaos.schedule import FaultSchedule

        try:
            FaultSchedule(dict(self.links), seed=self.seed)
        except (TypeError, ValueError) as e:
            raise TopologyError(f"bad netchaos schedule: {e}") from None
        object.__setattr__(
            self, "links", {str(k): v for k, v in dict(self.links).items()}
        )


@dataclasses.dataclass(frozen=True)
class ReconcilePolicy:
    """How the reconcile loop itself acts: tick cadence, per-resource
    act backoff, and the topology-wide restart-budget circuit breaker."""

    poll_interval_s: float = 0.25
    backoff_base_s: float = 0.5
    backoff_max_s: float = 30.0
    #: more than this many heal actions inside ``budget_window_s`` opens
    #: the circuit topology-wide (healing pauses until the window drains
    #: to half the budget) — a crash loop anywhere must degrade to a
    #: visible incident, not a fork storm
    restart_budget: int = 64
    budget_window_s: float = 300.0

    def __post_init__(self):
        if self.poll_interval_s <= 0:
            raise TopologyError("reconcile.poll_interval_s must be > 0")
        if self.backoff_base_s < 0 or self.backoff_max_s < self.backoff_base_s:
            raise TopologyError(
                "need 0 <= reconcile.backoff_base_s <= backoff_max_s, got "
                f"{self.backoff_base_s}/{self.backoff_max_s}"
            )
        if self.restart_budget < 0 or self.budget_window_s <= 0:
            raise TopologyError(
                "reconcile.restart_budget must be >= 0 and "
                "budget_window_s > 0"
            )

    def backoff_s(self, consecutive_failures: int) -> float:
        n = max(1, int(consecutive_failures))
        return min(self.backoff_max_s, self.backoff_base_s * (2 ** (n - 1)))


#: the trainer/task/env mode block — the cross-section rules below need it
@dataclasses.dataclass(frozen=True)
class ModeTopology:
    task: str = "train"
    trainer: str = "tpu_ba3c"
    env: str = "cpp:pong"
    overlap: bool = False
    fleet_accum: int = 1
    steps_per_epoch: int = 6000
    steps_per_dispatch: int = 1
    rollout_dtype: str = "float32"
    quant_spec: str = ""
    quant_calibrate: int = 0

    def __post_init__(self):
        if self.task not in ("train", "eval", "play", "dump_config"):
            raise TopologyError(f"unknown mode.task {self.task!r}")
        if self.fleet_accum < 1:
            raise TopologyError(
                f"mode.fleet_accum must be >= 1, got {self.fleet_accum}"
            )
        if self.steps_per_dispatch < 1 or self.steps_per_epoch < 1:
            raise TopologyError("mode step counts must be >= 1")
        if self.rollout_dtype not in ("float32", "bfloat16", "int8"):
            raise TopologyError(
                f"unknown mode.rollout_dtype {self.rollout_dtype!r} "
                "(float32 | bfloat16 | int8)"
            )
        if self.quant_calibrate < 0:
            raise TopologyError(
                f"mode.quant_calibrate must be >= 0, got "
                f"{self.quant_calibrate}"
            )
        if self.rollout_dtype == "int8":
            if bool(self.quant_spec) == bool(self.quant_calibrate):
                raise TopologyError(
                    "rollout_dtype int8 needs exactly ONE calibration "
                    "source: a frozen quant_spec file OR quant_calibrate N "
                    "live batches (docs/ingest.md)"
                )
        elif self.quant_spec or self.quant_calibrate:
            raise TopologyError(
                "quant_spec/quant_calibrate calibrate the int8 rung — "
                f"they do not apply to rollout_dtype {self.rollout_dtype!r}"
            )


@dataclasses.dataclass(frozen=True)
class TopologySpec:
    """The whole deployment, as one reviewable document.

    ``fleets`` carries one FleetSpec per actor fleet (empty = external
    fleets, supervised on their own hosts); ``learner``/``pod``/
    ``serving`` are optional sections (absent = that plane is not part of
    this topology); ``chaos``/``netchaos`` make a certification run's
    fault schedule part of the document it certifies; ``reconcile`` is
    the loop's own policy. JSON round-trips losslessly and every level
    rejects unknown fields.
    """

    version: int = 1
    mode: ModeTopology = dataclasses.field(default_factory=ModeTopology)
    fleets: Tuple[FleetSpec, ...] = ()
    learner: Optional[LearnerTopology] = None
    pod: Optional[PodTopology] = None
    serving: Optional[ServingTopology] = None
    chaos: Optional[ChaosTopology] = None
    netchaos: Optional[NetChaosTopology] = None
    reconcile: ReconcilePolicy = dataclasses.field(
        default_factory=ReconcilePolicy
    )

    def __post_init__(self):
        if self.version != 1:
            raise TopologyError(
                f"unknown topology version {self.version!r} (this tree "
                "speaks version 1)"
            )
        object.__setattr__(self, "fleets", tuple(self.fleets))
        self._validate_cross_sections()

    # -- the cross-section rules (cli.py's old inline validation) ---------
    def _validate_cross_sections(self) -> None:
        m = self.mode
        n_fleets = len(self.fleets)
        if m.task == "train" and m.env.startswith("zmq:") and any(
            not (f.pipe_c2s and f.pipe_s2c) for f in self.fleets
        ):
            raise TopologyError(
                "env zmq: means external env-server fleets feed this "
                "learner — give them reachable endpoints via "
                "pipe_c2s/pipe_s2c (e.g. tcp://0.0.0.0:5555 / "
                "tcp://0.0.0.0:5556)"
            )
        if (
            m.steps_per_dispatch > 1
            and m.steps_per_epoch % m.steps_per_dispatch
        ):
            raise TopologyError(
                f"steps_per_dispatch {m.steps_per_dispatch} must divide "
                f"steps_per_epoch {m.steps_per_epoch}"
            )
        if m.overlap and m.trainer != "tpu_fused_ba3c":
            raise TopologyError(
                "overlap splits the FUSED trainer's program in two — it "
                "requires trainer tpu_fused_ba3c (the ZMQ trainers "
                "already overlap actors and learner across processes)"
            )
        if n_fleets > 1 and (
            m.task != "train" or m.trainer == "tpu_fused_ba3c"
        ):
            raise TopologyError(
                "multiple fleets run against the ZMQ-plane trainers' "
                "train task — the fused trainer has no actor plane (its "
                "macro-batching knob is fleet_accum with overlap), and "
                "eval/play spawn no fleet"
            )
        if (
            m.rollout_dtype == "int8"
            and m.trainer == "tpu_fused_ba3c"
            and not m.overlap
        ):
            raise TopologyError(
                "rollout_dtype int8 on the fused trainer quantizes the "
                "ACTOR program's params snapshot — it requires overlap "
                "(the monolithic fused program has no separate actor "
                "forward to quantize)"
            )
        if m.fleet_accum > 1 and not m.overlap:
            raise TopologyError(
                "fleet_accum accumulates rollout windows in the overlap "
                "trainer's macro learner — it requires trainer "
                "tpu_fused_ba3c with overlap (ZMQ-plane macro-batching "
                "is multiple fleets)"
            )
        if self.serving is not None and (
            m.task != "train" or m.trainer == "tpu_fused_ba3c"
        ):
            raise TopologyError(
                "the serving section configures the predictor serving "
                "plane — it applies to the ZMQ-plane trainers' train "
                "task only (the fused trainer serves actions inside its "
                "compiled program; eval/play are synchronous)"
            )
        if (
            self.serving is not None
            and self.serving.canary_autopromote
            and n_fleets > 1
        ):
            raise TopologyError(
                "serving.canary_autopromote decides per router; with "
                "multiple fleets there are N independent routers and one "
                "canary decision must not be made N times — run it "
                "single-fleet"
            )
        if n_fleets and m.env.startswith("zmq:") and any(
            f.fleet_min != f.fleet_size or f.fleet_max != f.fleet_size
            for f in self.fleets
        ):
            raise TopologyError(
                "fleet_min/fleet_max size a LOCALLY-supervised env fleet "
                "— external zmq: fleets are supervised on their own "
                "hosts (scripts/launch_env_fleet.py)"
            )
        # a derived-pipe collision is a spec bug, not a runtime surprise
        pipes = [a for f in self.fleets for a in (f.pipe_c2s, f.pipe_s2c) if a]
        if len(set(pipes)) != len(pipes):
            raise TopologyError(
                f"fleet pipe addresses collide across {n_fleets} fleets: "
                f"{pipes}"
            )

    # -- (de)serialization -------------------------------------------------
    def to_doc(self) -> Dict[str, Any]:
        doc: Dict[str, Any] = {
            "version": self.version,
            "mode": dataclasses.asdict(self.mode),
            "fleets": [dataclasses.asdict(f) for f in self.fleets],
            "reconcile": dataclasses.asdict(self.reconcile),
        }
        for name in ("learner", "pod", "serving", "chaos", "netchaos"):
            section = getattr(self, name)
            if section is not None:
                doc[name] = dataclasses.asdict(section)
        return doc

    def to_json(self) -> str:
        return json.dumps(self.to_doc(), indent=2, sort_keys=True)

    @classmethod
    def from_doc(cls, doc: Any) -> "TopologySpec":
        if not isinstance(doc, Mapping):
            raise TopologyError(
                f"topology spec must be a JSON object, got "
                f"{type(doc).__name__}"
            )
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = sorted(set(doc) - known)
        if unknown:
            raise TopologyError(f"unknown topology fields: {unknown}")
        kw: Dict[str, Any] = {}
        if "version" in doc:
            kw["version"] = doc["version"]
        if "mode" in doc:
            kw["mode"] = _dataclass_from_doc(ModeTopology, doc["mode"], "mode")
        fleets_doc = doc.get("fleets", [])
        if not isinstance(fleets_doc, (list, tuple)):
            raise TopologyError(
                f"fleets must be a JSON array, got "
                f"{type(fleets_doc).__name__}"
            )
        fleets = []
        for i, fd in enumerate(fleets_doc):
            try:
                fleets.append(
                    _dataclass_from_doc(FleetSpec, fd, f"fleets[{i}]")
                )
            except ValueError as e:  # FleetSpec's own __post_init__ bounds
                raise TopologyError(str(e)) from None
        kw["fleets"] = tuple(fleets)
        for name, section_cls in (
            ("learner", LearnerTopology),
            ("pod", PodTopology),
            ("serving", ServingTopology),
            ("chaos", ChaosTopology),
            ("netchaos", NetChaosTopology),
        ):
            if doc.get(name) is not None:
                kw[name] = _dataclass_from_doc(
                    section_cls, doc[name], name
                )
        if "reconcile" in doc:
            kw["reconcile"] = _dataclass_from_doc(
                ReconcilePolicy, doc["reconcile"], "reconcile"
            )
        try:
            return cls(**kw)
        except TopologyError:
            raise
        except (TypeError, ValueError) as e:
            raise TopologyError(f"bad topology spec: {e}") from None

    @classmethod
    def from_json(cls, text: str) -> "TopologySpec":
        try:
            doc = json.loads(text)
        except json.JSONDecodeError as e:
            raise TopologyError(f"topology spec is not valid JSON: {e}")
        return cls.from_doc(doc)

    @classmethod
    def load(cls, path: str) -> "TopologySpec":
        try:
            with open(path) as fh:
                text = fh.read()
        except OSError as e:
            raise TopologyError(f"cannot read topology spec: {e}")
        return cls.from_json(text)

    # -- flags -> spec (the cli.py migration path) -------------------------
    @classmethod
    def from_flags(cls, args) -> "TopologySpec":
        """Build the spec a cli.py flag set describes (``--dump_topology``
        emits exactly this). Raises TopologyError for every combo the old
        inline validation block rejected — the rules live HERE now."""
        if getattr(args, "fleets", 1) < 1:
            raise TopologyError(f"--fleets must be >= 1, got {args.fleets}")
        if args.fleets > 1 and (
            args.task != "train" or args.trainer == "tpu_fused_ba3c"
        ):
            raise TopologyError(
                "--fleets N runs N actor fleets against the ZMQ-plane "
                "trainers' train task — the fused trainer has no actor "
                "plane (its macro-batching knob is --fleet_accum with "
                "--overlap), and eval/play spawn no fleet"
            )
        if getattr(args, "serve_replicas", 1) < 1:
            raise TopologyError(
                f"--serve_replicas must be >= 1, got {args.serve_replicas}"
            )
        if bool(args.pipe_c2s) != bool(args.pipe_s2c):
            raise TopologyError(
                "--pipe_c2s and --pipe_s2c must be given together"
            )
        if (
            args.task == "train"
            and args.env.startswith("zmq:")
            and not (args.pipe_c2s and args.pipe_s2c)
        ):
            raise TopologyError(
                "--env zmq: means external env-server fleets feed this "
                "learner — give them reachable endpoints via --pipe_c2s/"
                "--pipe_s2c (e.g. tcp://0.0.0.0:5555 / tcp://0.0.0.0:5556)"
            )
        mode = ModeTopology(
            task=args.task,
            trainer=args.trainer,
            env=args.env,
            overlap=bool(getattr(args, "overlap", False)),
            fleet_accum=getattr(args, "fleet_accum", 1),
            steps_per_epoch=args.steps_per_epoch,
            steps_per_dispatch=getattr(args, "steps_per_dispatch", 1),
            rollout_dtype=getattr(args, "rollout_dtype", "float32"),
            quant_spec=getattr(args, "quant_spec", None) or "",
            quant_calibrate=int(getattr(args, "quant_calibrate", 0) or 0),
        )
        fleets: Tuple[FleetSpec, ...] = ()
        external = args.env.startswith("zmq:")
        spawns_fleet = args.task == "train" and mode.trainer != "tpu_fused_ba3c"
        if (args.fleet_min or args.fleet_max) and (
            args.task != "train" or external
        ):
            raise TopologyError(
                "--fleet_min/--fleet_max size a LOCALLY-supervised env "
                "fleet — external zmq: fleets are supervised on their own "
                "hosts (scripts/launch_env_fleet.py), and eval/play spawn "
                "no fleet"
            )
        if (
            args.fleet_min
            and args.fleet_max
            and args.fleet_min > args.fleet_max
        ):
            raise TopologyError(
                f"--fleet_min {args.fleet_min} > --fleet_max "
                f"{args.fleet_max}"
            )
        if spawns_fleet:
            from distributed_ba3c_tpu.actors.fleet import fleet_pipes

            n_fleets = args.fleets
            c2s = args.pipe_c2s or "ipc://ba3c-c2s"
            s2c = args.pipe_s2c or "ipc://ba3c-s2c"
            sims = args.simulator_procs or 50
            per_fleet = max(1, sims // n_fleets)
            if external:
                per, wire, n_servers = 16, "block", per_fleet
            elif args.env.startswith("cpp:"):
                per = min(16, per_fleet)
                wire = args.wire if args.wire != "auto" else "block"
                n_servers = (per_fleet + per - 1) // per
            else:
                per, wire, n_servers = 1, "per-env", per_fleet
            lo = args.fleet_min or n_servers
            hi = args.fleet_max or n_servers
            if not lo <= n_servers <= hi:
                raise TopologyError(
                    f"launch fleet size {n_servers} servers is outside "
                    f"[--fleet_min {lo}, --fleet_max {hi}] — size the "
                    "launch fleet (--simulator_procs, split per fleet) "
                    "inside the bounds"
                )
            game = (
                args.env.split(":", 1)[1]
                if args.env.startswith("cpp:")
                else "pong"
            )
            built = []
            for k in range(n_fleets):
                c2s_k, s2c_k = fleet_pipes(c2s, s2c, k)
                try:
                    built.append(FleetSpec(
                        pipe_c2s=c2s_k, pipe_s2c=s2c_k, game=game,
                        envs_per_server=per, wire=wire,
                        fleet_size=n_servers, fleet_min=min(lo, n_servers),
                        fleet_max=max(hi, n_servers),
                    ))
                except ValueError as e:
                    raise TopologyError(str(e)) from None
            fleets = tuple(built)
        serving = None
        if (
            args.serve_slo_ms or args.canary_load or args.shadow_load
            or args.canary_fraction > 0
            or args.serve_replicas > 1 or args.serve_replicas_max
        ):
            serving = ServingTopology(
                replicas=args.serve_replicas,
                replicas_max=args.serve_replicas_max or 0,
                slo_ms=args.serve_slo_ms or 0.0,
                canary_load=args.canary_load or "",
                canary_fraction=args.canary_fraction,
                canary_autopromote=bool(args.canary_autopromote),
                shadow_load=args.shadow_load or "",
                autoscale_interval_s=args.autoscale_interval,
            )
        learner = None
        if args.task == "train" and args.logdir:
            learner = LearnerTopology(
                logdir=args.logdir,
                train_args=("--logdir", args.logdir),
            )
        return cls(
            mode=mode, fleets=fleets, learner=learner, serving=serving,
        )
