"""Telemetry-driven autoscaling: backpressure signals → fleet-size moves.

The plane's own physics picks the signals (docs/actor_plane.md): the wire
is LOCKSTEP, so the train queue's fill fraction says everything about the
producer/consumer balance —

- the queue sitting near EMPTY with no blocked puts means the learner
  drains faster than the fleet produces: the learner is starved, add
  servers;
- blocked puts ticking (the master waited on a FULL queue) or the queue
  riding near full means the fleet outruns the learner: backpressure is
  already pausing actors, so the marginal server adds sync latency and
  host load but zero throughput — retire servers.

Policy is deliberately bang-bang with hysteresis (watermark deadband +
``patience`` consecutive ticks + post-decision cooldown): fleet moves cost
a process spawn and a wire (re)handshake, so the loop must be stable
against one noisy tick, and every decision must be explainable from one
snapshot — the decision's inputs ride into the flight recorder with it.

Signals come from :meth:`SimulatorMaster.fleet_snapshot` in-process (the
usual layout: the supervisor lives in the learner process) or from the
``--telemetry_port`` ``/json`` endpoint for an out-of-process supervisor —
both read the SAME telemetry series the scrape endpoint exports.
"""

from __future__ import annotations

import json
import time
import urllib.request
from typing import Callable, Dict, Optional, Tuple

from distributed_ba3c_tpu import telemetry
from distributed_ba3c_tpu.orchestrate.supervisor import FleetSupervisor
from distributed_ba3c_tpu.utils import logger
from distributed_ba3c_tpu.utils.concurrency import StoppableThread


def master_signals(master) -> Callable[[], Dict[str, float]]:
    """Signal source over a live master's fleet introspection hook."""
    return master.fleet_snapshot


def http_signals(
    url: str, timeout_s: float = 2.0, fleet: Optional[int] = None
) -> Callable[[], Dict[str, float]]:
    """Signal source over a ``--telemetry_port`` ``/json`` endpoint (for a
    supervisor running outside the learner process).

    ``fleet`` addresses ONE master among several on the scrape target: a
    multi-fleet learner (``--fleets N``) exports each master under its
    per-fleet role (``master.f<k>``, telemetry.fleet_role — the per-fleet
    scrape label), and an actor host supervising fleet k's env servers
    must autoscale on THAT fleet's queue fill, not on whichever master
    happened to register last (the pre-fleet exporter assumed one master
    registry per process). ``None`` keeps the single-fleet ``master``
    role. A missing role fails LOUDLY — all-zero signals would read as
    permanent starvation and ratchet the fleet to fleet_max on a typo'd
    fleet index.
    """
    if not url.endswith("/json"):
        url = url.rstrip("/") + "/json"
    role = telemetry.fleet_role("master", fleet)

    def scrape() -> Dict[str, float]:
        with urllib.request.urlopen(url, timeout=timeout_s) as r:
            doc = json.loads(r.read().decode())
        master = doc.get(role)
        if master is None:
            raise KeyError(
                f"scrape target {url} exports no {role!r} registry "
                f"(roles: {sorted(doc)}) — wrong --fleet index, or the "
                "learner is not running --fleets"
            )

        def val(name: str) -> float:
            return float(master.get(name, {}).get("value", 0.0))

        return {
            "clients": val("clients"),
            "queue_depth": val("train_queue_depth"),
            "queue_maxsize": val("train_queue_capacity"),
            "blocked_puts_total": val("queue_blocked_puts_total"),
            "datapoints_total": val("datapoints_total"),
        }

    return scrape


class AutoscalerPolicy:
    """The pure decision function (unit-testable without any plane).

    ``decide(signals)`` returns ``(delta, reason)`` with delta in
    ``{-step, 0, +step}``. Stateful: it tracks consecutive
    starved/backpressured ticks and the post-decision cooldown.
    """

    def __init__(
        self,
        low_fill: float = 0.25,
        high_fill: float = 0.75,
        patience: int = 3,
        cooldown_ticks: int = 5,
        step: int = 1,
    ):
        if not 0 <= low_fill < high_fill <= 1:
            raise ValueError(
                f"need 0 <= low_fill < high_fill <= 1, got "
                f"{low_fill}/{high_fill}"
            )
        self.low_fill = low_fill
        self.high_fill = high_fill
        self.patience = max(1, patience)
        self.cooldown_ticks = max(0, cooldown_ticks)
        self.step = max(1, step)
        self._starved = 0
        self._pressured = 0
        self._cooldown = 0
        self._last_blocked = None  # None until the first tick baselines it

    def decide(self, s: Dict[str, float]) -> Tuple[int, str]:
        depth = float(s.get("queue_depth", 0))
        cap = float(s.get("queue_maxsize", 0))
        # no known bound (unbounded custom queue, or a scrape target that
        # predates the train_queue_capacity gauge) -> the fill fraction is
        # UNKNOWN, not zero: a 0.0 sentinel would read as permanently
        # starved and ratchet the fleet to fleet_max on no signal at all.
        # The blocked-put delta still works capacity-free, so scale-DOWN
        # stays available.
        fill = depth / cap if cap > 0 else None
        blocked = float(s.get("blocked_puts_total", 0))
        if self._last_blocked is None:
            # first tick baselines the counter — a delta against 0 would
            # read the whole pre-attach history as fresh backpressure
            self._last_blocked = blocked
            return 0, ""
        blocked_delta = blocked - self._last_blocked
        self._last_blocked = blocked
        if self._cooldown > 0:
            self._cooldown -= 1
            return 0, ""
        if blocked_delta > 0 or (fill is not None and fill >= self.high_fill):
            self._pressured += 1
            self._starved = 0
        elif fill is not None and fill <= self.low_fill:
            self._starved += 1
            self._pressured = 0
        else:
            self._starved = self._pressured = 0
        if self._pressured >= self.patience:
            self._pressured = self._starved = 0
            self._cooldown = self.cooldown_ticks
            return -self.step, (
                f"backpressure: queue fill "
                f"{'unknown' if fill is None else format(fill, '.2f')}, "
                f"+{blocked_delta:.0f} blocked puts — the learner is the "
                "bottleneck, extra servers only add latency"
            )
        if self._starved >= self.patience:
            self._pressured = self._starved = 0
            self._cooldown = self.cooldown_ticks
            return self.step, (
                f"starved: queue fill {fill:.2f} with no blocked puts — "
                "the learner outruns the fleet"
            )
        return 0, ""


class Autoscaler(StoppableThread):
    """The policy loop: scrape → decide → ``supervisor.scale_by``.

    Every decision (and its input snapshot) is flight-recorded and the
    tick/decision counts ride ``tele/orchestrator/*`` — a scale event in a
    postmortem always comes with the signals that caused it.
    """

    def __init__(
        self,
        supervisor: FleetSupervisor,
        signals: Callable[[], Dict[str, float]],
        policy: Optional[AutoscalerPolicy] = None,
        interval_s: float = 2.0,
    ):
        super().__init__(daemon=True, name="Autoscaler")
        self.supervisor = supervisor
        self._signals = signals
        self.policy = policy or AutoscalerPolicy()
        self.interval_s = interval_s
        self._flight = telemetry.flight_recorder()
        tele = telemetry.registry("orchestrator")
        self._c_ticks = tele.counter("autoscale_ticks_total")
        self._c_decisions = tele.counter("autoscale_decisions_total")
        self._c_errors = tele.counter("autoscale_signal_errors_total")

    def run(self) -> None:
        while not self.stopped():
            self.tick()
            self._stop_evt.wait(self.interval_s)

    def tick(self) -> None:
        """One scrape→decide→act step (public so tests and the chaos
        bench can drive the loop deterministically)."""
        self._c_ticks.inc()
        try:
            s = self._signals()
        except Exception as e:
            # a torn-down master / unreachable endpoint must not kill the
            # loop — skip the tick, count it, keep watching
            self._c_errors.inc()
            logger.warn("autoscaler signal scrape failed: %s", e)
            return
        delta, reason = self.policy.decide(s)
        if delta == 0:
            return
        old = self.supervisor.target
        new = self.supervisor.scale_by(delta, reason=reason)
        self._c_decisions.inc()
        self._flight.record(
            "scale_decision",
            delta=delta,
            frm=old,
            to=new,
            reason=reason[:200],
            queue_depth=s.get("queue_depth"),
            queue_maxsize=s.get("queue_maxsize"),
            blocked_puts_total=s.get("blocked_puts_total"),
        )
