"""Operator entry point for the orchestration plane — three modes:

**Learner checkpoint-failover** (default)::

    python -m distributed_ba3c_tpu.orchestrate \\
        --logdir runs/x --max_restarts 5 --stall_secs 300 -- \\
        --trainer tpu_fused_ba3c --env jax:pong --logdir runs/x [...]

Everything after ``--`` goes to train.py verbatim (it must include
``--logdir`` matching ours and must NOT include ``--load`` — the
supervisor adds it whenever a finalized checkpoint exists). This is
scripts/run_with_resume.sh with the failover counted, flight-recorded
and dumped (docs/orchestration.md).

**Multi-host worker launch** (``--multihost``, retiring
scripts/launch_multihost.sh — the shell script is now a shim onto this)::

    python -m distributed_ba3c_tpu.orchestrate \\
        --multihost "host1:9900,host2:9900" -- --logdir runs/x [...]

Rank = SLURM_PROCID or this hostname's position in the list; exit 75
(lost lockstep) relaunches under the same finalized-checkpoint resume
gate the learner supervisor uses (orchestrate/multihost.py).

**Pod mode** (``--pod_hosts N``, docs/pod.md): supervise N actor-host
processes against one in-process bounded-staleness learner on the given
tcp pipe base::

    python -m distributed_ba3c_tpu.orchestrate --pod_hosts 2 \\
        --pipe_c2s tcp://127.0.0.1:15555 --pipe_s2c tcp://127.0.0.1:15556 \\
        --logdir runs/pod --updates 500

**Topology mode** (``--topology spec.json``, docs/topology.md): run ONE
reconciler over a whole declarative :class:`TopologySpec` — env-server
fleets, pod actor hosts, and the supervised learner, healed to spec by
the generic observe→diff→act loop (orchestrate/reconcile.py)::

    python -m distributed_ba3c_tpu.orchestrate --topology spec.json

Emit a spec from any existing cli.py flag set with ``--dump_topology``
(migration aid). A serving section needs the learner process's router:
it rides INSIDE the learner child (the spec's ``learner.train_args``
carry the ``--serve_*`` flags), not in this orchestrator process.
"""

from __future__ import annotations

import argparse
import sys
import time
from typing import List, Optional

from distributed_ba3c_tpu import telemetry
from distributed_ba3c_tpu.orchestrate.learner import LearnerSupervisor


def make_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m distributed_ba3c_tpu.orchestrate",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("--logdir", default=None, help="the run's logdir (same value train.py gets); required outside --multihost")
    p.add_argument(
        "--topology", default=None, metavar="SPEC.JSON",
        help="topology mode: reconcile the whole declarative TopologySpec "
        "(fleets, pod hosts, supervised learner) with one generic loop "
        "(docs/topology.md)",
    )
    p.add_argument("--max_restarts", type=int, default=5)
    p.add_argument(
        "--stall_secs", type=float, default=0,
        help="kill + resume when log.log stops moving for this long "
        "(0 = crash-only failover, no stall watchdog)",
    )
    # -- multi-host mode ---------------------------------------------------
    p.add_argument(
        "--multihost", default=None, metavar="HOST1:P,HOST2:P",
        help="run this host's worker rank of a multi-host training job "
        "(rank from SLURM_PROCID or hostname position); train.py args "
        "after '--'. Replaces scripts/launch_multihost.sh",
    )
    # -- pod mode (docs/pod.md) --------------------------------------------
    p.add_argument(
        "--pod_hosts", type=int, default=0,
        help="pod mode: supervise N actor-host processes against one "
        "in-process bounded-staleness learner (0 = off)",
    )
    p.add_argument("--pipe_c2s", default="tcp://127.0.0.1:15555", help="pod mode: base pipe pair the pod channels derive from (pod/wire.py)")
    p.add_argument("--pipe_s2c", default="tcp://127.0.0.1:15556")
    p.add_argument("--updates", type=int, default=0, help="pod mode: stop after this many learner updates (0 = run until interrupted)")
    p.add_argument("--max_staleness", type=int, default=-1, help="pod mode: reject blocks more than this many params versions stale (-1 = measure only)")
    p.add_argument("--publish_every", type=int, default=1, help="pod mode: publish params every N updates")
    p.add_argument("--pod_env", default="fake", help="pod mode: each host's env (fake | cpp:<game>)")
    p.add_argument("--pod_sims", type=int, default=4, help="pod mode: simulators (fake) / envs (cpp) per host")
    p.add_argument("--pod_unroll_len", type=int, default=5)
    p.add_argument("--pod_segments", type=int, default=16, help="pod mode: unroll segments per shipped block (the block's B)")
    p.add_argument("--pod_image_size", type=int, default=84)
    p.add_argument("--pod_frame_history", type=int, default=4)
    p.add_argument("--pod_num_actions", type=int, default=4)
    p.add_argument("--pod_fc_units", type=int, default=512)
    p.add_argument("--pod_predict_batch_size", type=int, default=16)
    return p


def run_topology(spec_path: str, p: argparse.ArgumentParser) -> int:
    """Reconcile a whole TopologySpec with one generic loop: fleets, pod
    actor hosts and the supervised learner as Reconcilable resources.
    Spec problems are usage errors (exit 2), never tracebacks."""
    from distributed_ba3c_tpu.orchestrate.pod import PodSupervisor, host_argv
    from distributed_ba3c_tpu.orchestrate.reconcile import (
        FleetResource,
        LearnerResource,
        Reconciler,
    )
    from distributed_ba3c_tpu.orchestrate.supervisor import FleetSupervisor
    from distributed_ba3c_tpu.orchestrate.topology import (
        TopologyError,
        TopologySpec,
    )

    try:
        spec = TopologySpec.load(spec_path)
    except TopologyError as e:
        p.error(str(e))
    if spec.serving is not None and spec.learner is None:
        p.error(
            "a serving section rides INSIDE the learner process (its "
            "router lives there) — give the spec a learner whose "
            "train_args carry the --serve_* flags, or drop the section"
        )
    if spec.learner is not None:
        telemetry.configure(spec.learner.logdir)
    rec = Reconciler(policy=spec.reconcile)  # ba3cflow: disable=F5 — the finally's rec.close() stops AND joins the loop thread (Reconciler.close)
    for k, fleet in enumerate(spec.fleets):
        rec.add(FleetResource(f"fleet{k}", FleetSupervisor(fleet)))
    if spec.pod is not None:
        pod = spec.pod
        if not (pod.pipe_c2s and pod.pipe_s2c):
            p.error(
                "pod.pipe_c2s/pod.pipe_s2c must name the learner's pipe "
                "pair the supervised hosts connect to"
            )
        rec.add(FleetResource(
            "pod-hosts",
            PodSupervisor(
                pod.hosts,
                lambda i: host_argv(
                    i, pod.pipe_c2s, pod.pipe_s2c, env=pod.env,
                    n_sims=pod.sims_per_host,
                    max_staleness=max(0, pod.max_staleness),
                ),
                backoff_base_s=pod.backoff_base_s,
            ),
            kind="pod",
        ))
    learner_res = None
    if spec.learner is not None:
        lt = spec.learner
        try:
            sup = LearnerSupervisor(
                lt.logdir,
                list(lt.train_args),
                max_restarts=lt.max_restarts,
                stall_secs=lt.stall_secs,
                startup_grace_s=lt.startup_grace_s,
                poll_s=lt.poll_s,
            )
        except ValueError as e:  # train_args --logdir/--load misuse
            p.error(str(e))
        learner_res = rec.add(LearnerResource("learner", sup))
    if not rec.resources():
        p.error(
            "the topology names nothing this orchestrator can run — add "
            "fleets, a pod section, or a learner section"
        )
    rec.start()
    try:
        while True:
            if learner_res is not None and learner_res.final_rc is not None:
                return learner_res.final_rc
            time.sleep(spec.reconcile.poll_interval_s)
    except KeyboardInterrupt:
        return 130
    finally:
        rec.close()


def main(argv: Optional[List[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else list(argv)
    if "--" in argv:
        split = argv.index("--")
        ours, train_args = argv[:split], argv[split + 1 :]
    else:
        ours, train_args = argv, []
    p = make_parser()
    args = p.parse_args(ours)

    if args.multihost and args.pod_hosts:
        p.error("--multihost and --pod_hosts are different modes — pick one")

    if args.topology:
        if args.multihost or args.pod_hosts:
            p.error(
                "--topology is its own mode: the spec document carries "
                "the pod/learner sections"
            )
        if train_args:
            p.error(
                "--topology takes no train.py arguments after '--' — the "
                "spec's learner.train_args carry them"
            )
        return run_topology(args.topology, p)

    if args.multihost:
        from distributed_ba3c_tpu.orchestrate.multihost import MultihostLauncher

        if not train_args:
            p.error("no train.py arguments after '--'")
        return MultihostLauncher(args.multihost, train_args).run()

    if args.pod_hosts:
        from distributed_ba3c_tpu.orchestrate.pod import run_pod

        if train_args:
            # pod mode runs no train.py — silently ignoring these flags
            # would measure a multi-hour capture on the wrong workload
            p.error(
                "pod mode takes no train.py arguments after '--' — the "
                "pod's workload is shaped by the --pod_* flags"
            )
        if args.logdir:
            telemetry.configure(args.logdir)
        return run_pod(args)

    if not args.logdir:
        p.error("--logdir is required (it gates the stall watchdog and the resume path)")
    if not train_args:
        p.error("no train.py arguments after '--'")
    telemetry.configure(args.logdir)
    sup = LearnerSupervisor(
        args.logdir,
        train_args,
        max_restarts=args.max_restarts,
        stall_secs=args.stall_secs,
    )
    return sup.run()


if __name__ == "__main__":
    sys.exit(main())
