"""Operator entry point for learner checkpoint-failover:

    python -m distributed_ba3c_tpu.orchestrate \\
        --logdir runs/x --max_restarts 5 --stall_secs 300 -- \\
        --trainer tpu_fused_ba3c --env jax:pong --logdir runs/x [...]

Everything after ``--`` goes to train.py verbatim (it must include
``--logdir`` matching ours and must NOT include ``--load`` — the
supervisor adds it whenever a finalized checkpoint exists). This is
scripts/run_with_resume.sh with the failover counted, flight-recorded
and dumped (docs/orchestration.md).
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

from distributed_ba3c_tpu import telemetry
from distributed_ba3c_tpu.orchestrate.learner import LearnerSupervisor


def main(argv: Optional[List[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else list(argv)
    if "--" in argv:
        split = argv.index("--")
        ours, train_args = argv[:split], argv[split + 1 :]
    else:
        ours, train_args = argv, []
    p = argparse.ArgumentParser(
        prog="python -m distributed_ba3c_tpu.orchestrate",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("--logdir", required=True, help="the run's logdir (same value train.py gets)")
    p.add_argument("--max_restarts", type=int, default=5)
    p.add_argument(
        "--stall_secs", type=float, default=0,
        help="kill + resume when log.log stops moving for this long "
        "(0 = crash-only failover, no stall watchdog)",
    )
    args = p.parse_args(ours)
    if not train_args:
        p.error("no train.py arguments after '--'")
    telemetry.configure(args.logdir)
    sup = LearnerSupervisor(
        args.logdir,
        train_args,
        max_restarts=args.max_restarts,
        stall_secs=args.stall_secs,
    )
    return sup.run()


if __name__ == "__main__":
    sys.exit(main())
