"""Pod orchestration: N supervised actor-host processes, one learner.

The composition ROADMAP item 2 asked for, assembled from machinery that
already exists: :class:`FleetSupervisor` supervises whole ACTOR HOSTS
(``python -m distributed_ba3c_tpu.pod.host`` subprocesses) exactly the
way it supervises env servers — respawn with backoff, restart-budget
circuit breaker, every decision flight-recorded — while the learner side
is the in-process :class:`PodLearnerPlane` (publisher + ingest + the
bounded-staleness learner). The chaos host-loss scenario SIGKILLs a whole
host's process GROUP mid-run: the learner keeps training on the
surviving hosts' blocks, the supervisor respawns the host, and its cache
rejoins at the current version over the fetch channel — no learner
restart (scripts/pod_bench.py gates on it).

Entry point::

    python -m distributed_ba3c_tpu.orchestrate --pod_hosts 2 \\
        --pipe_c2s tcp://127.0.0.1:15555 --pipe_s2c tcp://127.0.0.1:15556 \\
        --logdir runs/pod --updates 500
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
from typing import Callable, List, Optional

# NO top-level jax import: orchestrate/ is imported by jax-free actor-host
# launchers (scripts/launch_env_fleet.py's contract); only the learner
# plane below touches jax, lazily

from distributed_ba3c_tpu import telemetry
from distributed_ba3c_tpu.config import BA3CConfig
from distributed_ba3c_tpu.orchestrate.spec import FleetSpec
from distributed_ba3c_tpu.orchestrate.supervisor import FleetSupervisor
from distributed_ba3c_tpu.utils import logger

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


class _HostProc:
    """Process-like wrapper over one actor-host subprocess (the duck type
    FleetSupervisor's lifecycle expects: start/is_alive/terminate/kill/
    join/pid/exitcode). Owns its session, so kill/terminate act on the
    whole process GROUP — a SIGKILLed host must not leak its simulator
    children (they would otherwise survive as orphans parked in recv on
    the dead master's pipes)."""

    def __init__(self, argv: List[str]):
        self._argv = argv
        self._proc: Optional[subprocess.Popen] = None

    def start(self) -> None:
        env = dict(os.environ)
        # FORCED, not setdefault: actor hosts never claim a TPU — a
        # learner launched with JAX_PLATFORMS=tpu exported must not hand
        # N children a claim on the chip it holds (they would stall at
        # jax init and burn the respawn budget into the circuit breaker)
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = _REPO_ROOT + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else ""
        )
        self._proc = subprocess.Popen(
            self._argv, start_new_session=True, env=env
        )

    @property
    def pid(self) -> Optional[int]:
        return self._proc.pid if self._proc else None

    @property
    def exitcode(self) -> Optional[int]:
        return self._proc.returncode if self._proc else None

    def is_alive(self) -> bool:
        return self._proc is not None and self._proc.poll() is None

    def _signal_group(self, sig: int) -> None:
        if self._proc is None:
            return
        try:
            os.killpg(self._proc.pid, sig)
        except (OSError, ProcessLookupError):
            pass

    def terminate(self) -> None:
        self._signal_group(signal.SIGTERM)

    def kill(self) -> None:
        self._signal_group(signal.SIGKILL)

    def join(self, timeout: Optional[float] = None) -> None:
        if self._proc is None:
            return
        try:
            self._proc.wait(timeout)
        except subprocess.TimeoutExpired:
            pass


def host_argv(
    host_id: int,
    learner_c2s: str,
    learner_s2c: str,
    env: str = "fake",
    n_sims: int = 4,
    unroll_len: int = 5,
    segments_per_block: int = 16,
    max_staleness: int = 0,
    image_size: int = 84,
    frame_history: int = 4,
    num_actions: int = 4,
    fc_units: int = 512,
    predict_batch_size: int = 16,
    python: Optional[str] = None,
) -> List[str]:
    """The canonical actor-host launch line (one formula — the supervisor
    factory, the bench and the operator runbook must not drift)."""
    return [
        python or sys.executable, "-m", "distributed_ba3c_tpu.pod.host",
        "--host_id", str(host_id),
        "--learner_c2s", learner_c2s,
        "--learner_s2c", learner_s2c,
        "--env", env,
        "--n_sims", str(n_sims),
        "--unroll_len", str(unroll_len),
        "--segments_per_block", str(segments_per_block),
        "--max_staleness", str(max_staleness),
        "--image_size", str(image_size),
        "--frame_history", str(frame_history),
        "--num_actions", str(num_actions),
        "--fc_units", str(fc_units),
        "--predict_batch_size", str(predict_batch_size),
    ]


class PodSupervisor(FleetSupervisor):
    """FleetSupervisor whose slots are whole actor hosts.

    ``make_argv(host_id)`` builds the host launch line (:func:`host_argv`
    partial'd by the caller). Slot index == host id — a respawned host
    rejoins under the same identity, its cache re-fetching the current
    params version (the pod's incarnation-reset analogue)."""

    def __init__(
        self,
        n_hosts: int,
        make_argv: Callable[[int], List[str]],
        poll_interval_s: float = 0.25,
        backoff_base_s: float = 0.25,
    ):
        spec = FleetSpec(
            envs_per_server=1,
            wire="per-env",  # spec validation; the hosts own their wires
            fleet_size=n_hosts,
            fleet_min=n_hosts,
            fleet_max=n_hosts,
            backoff_base_s=backoff_base_s,
            backoff_max_s=10.0,
            stable_after_s=10.0,
        )
        super().__init__(
            spec,
            factory=lambda i: _HostProc(make_argv(i)),
            ident_prefix=lambda i: f"pod-host-{i}",
            poll_interval_s=poll_interval_s,
        )

    def sigkill_slot(self, idx: int) -> bool:
        """SIGKILL a host's whole process group (chaos host-loss): the
        host AND its simulator children die instantly, no goodbye on any
        wire — exactly losing the machine."""
        with self._lock:
            slot = self._slots.get(idx)
            proc = slot.proc if slot is not None else None
        if proc is None or not proc.is_alive():
            return False
        proc.kill()
        return True


class PodLearnerPlane:
    """The learner half of a pod, assembled: params publisher + stamped
    ingest + the bounded-staleness PodLearner, on localhost or real tcp.

    ``step_once`` consumes one stamped batch (or times out); the caller
    owns the loop — the orchestrate pod mode and scripts/pod_bench.py
    both drive it.
    """

    def __init__(
        self,
        cfg: BA3CConfig,
        pipe_c2s: str,
        pipe_s2c: str,
        max_staleness: Optional[int] = None,
        publish_every: int = 1,
        ingest_depth: int = 16,
        seed: int = 0,
        mesh=None,
    ):
        import jax

        from distributed_ba3c_tpu.models.a3c import BA3CNet
        from distributed_ba3c_tpu.ops.gradproc import make_optimizer
        from distributed_ba3c_tpu.parallel.mesh import make_mesh
        from distributed_ba3c_tpu.parallel.train_step import create_train_state
        from distributed_ba3c_tpu.pod.ingest import PodIngest
        from distributed_ba3c_tpu.pod.learner import (
            PodLearner,
            make_pod_learner_step,
        )
        from distributed_ba3c_tpu.pod.publisher import ParamsPublisher
        from distributed_ba3c_tpu.pod.wire import pod_endpoints

        self.cfg = cfg
        self.endpoints = pod_endpoints(pipe_c2s, pipe_s2c)
        model = BA3CNet(num_actions=cfg.num_actions, fc_units=cfg.fc_units)
        optimizer = make_optimizer(
            cfg.learning_rate, cfg.adam_epsilon, cfg.grad_clip_norm
        )
        # a 1-device mesh by default: host-fed block shapes are the hosts'
        # choice and must not have to divide a device count; a caller with
        # a real mesh (and host shapes sized for it) passes its own
        mesh = mesh or make_mesh(num_data=1, devices=jax.devices()[:1])
        step = make_pod_learner_step(model, optimizer, cfg, mesh)
        state = create_train_state(
            jax.random.PRNGKey(seed), model, cfg, optimizer
        )
        self.publisher = ParamsPublisher(self.endpoints)
        self.learner = PodLearner(
            step, state, cfg,
            publisher=self.publisher,
            max_staleness=max_staleness,
            publish_every=publish_every,
            # every buffered StampedBatch holds a stager slot: the ring
            # must cover the ingest depth (+ one staging, one in-flight)
            # or a backed-up learner degrades to per-block fresh
            # allocations — the cost the stager exists to remove
            stager_slots=ingest_depth + 2,
        )
        # the learner's own BlockStager on the ingest receive thread: the
        # wire→staging copy overlaps the learner step, and the learner
        # loop only pays the async device transfer (docs/ingest.md)
        self.ingest = PodIngest(
            self.endpoints, depth=ingest_depth, stager=self.learner.stager
        )

    def start(self) -> None:
        self.publisher.start()
        self.ingest.start()
        logger.info(
            "pod learner plane up: params %s / %s, experience %s",
            self.endpoints.params_pub, self.endpoints.params_fetch,
            self.endpoints.experience,
        )

    def step_once(self, timeout: float = 1.0) -> Optional[dict]:
        stamped = self.ingest.next_batch(timeout)
        if stamped is None:
            return None
        return self.learner.consume(stamped)

    def close(self) -> None:
        self.ingest.close()
        self.publisher.close()


def run_pod(args) -> int:
    """The orchestrate pod mode: learner in-process, hosts supervised."""
    cfg = BA3CConfig(
        image_size=(args.pod_image_size, args.pod_image_size),
        frame_history=args.pod_frame_history,
        num_actions=args.pod_num_actions,
        fc_units=args.pod_fc_units,
        local_time_max=args.pod_unroll_len,
        predict_batch_size=args.pod_predict_batch_size,
    )
    plane = PodLearnerPlane(
        cfg,
        args.pipe_c2s,
        args.pipe_s2c,
        max_staleness=args.max_staleness if args.max_staleness >= 0 else None,
        publish_every=args.publish_every,
    )
    plane.start()
    sup = PodSupervisor(
        args.pod_hosts,
        lambda i: host_argv(
            i, args.pipe_c2s, args.pipe_s2c,
            env=args.pod_env,
            n_sims=args.pod_sims,
            unroll_len=args.pod_unroll_len,
            segments_per_block=args.pod_segments,
            max_staleness=max(0, args.max_staleness),
            image_size=args.pod_image_size,
            frame_history=args.pod_frame_history,
            num_actions=args.pod_num_actions,
            fc_units=args.pod_fc_units,
            predict_batch_size=args.pod_predict_batch_size,
        ),
    )
    sup.start()
    reg = telemetry.registry("learner")
    try:
        updates = 0
        while args.updates <= 0 or updates < args.updates:
            m = plane.step_once(timeout=1.0)
            if m is not None:
                updates += 1
                if updates % 50 == 0:
                    logger.info(
                        "[pod] update %d (version %d, value_lag_mae %.4f, "
                        "ingested %d blocks)",
                        updates, plane.learner.version,
                        reg.gauge("value_lag_mae").value(),
                        int(reg.counter("pod_ingest_blocks_total").value()),
                    )
        return 0
    except KeyboardInterrupt:
        return 0
    finally:
        sup.stop()
        sup.join(timeout=5)
        sup.close()
        plane.close()
        telemetry.dump("pod run complete")
