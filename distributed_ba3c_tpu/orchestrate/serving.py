"""Serving orchestration: replica lifecycle, SLO autoscaling, canary loop.

The serving router (predict/router.py) only ROUTES; this module is the
control plane above it, built the way every orchestration layer here is
(docs/orchestration.md): pure decision functions driven by telemetry
signals, every decision flight-recorded WITH the input snapshot that
caused it, all lifecycle owned by one supervisor-shaped component.

Three pieces:

- :class:`ReplicaSet` — spawns/retires predictor replicas from a
  pluggable factory (real ``BatchedPredictor``s, bench null devices, test
  fakes all ride the same lifecycle), registers them with the router
  under monotonic incarnation ids (``r0, r1, …`` — a respawn is a NEW
  replica, so its telemetry series never merge with a corpse's), and
  clamps ``scale_to`` to the configured bounds.
- :class:`ServingScalerPolicy` + :class:`ReplicaAutoscaler` — the PR-7
  ``AutoscalerPolicy`` shape (bang-bang, watermark deadband, patience,
  cooldown) generalized to the serving SLO: the watermark is the routed
  plane's WINDOWED served-p99 and shed-rate (router.aggregate_signals),
  not queue fill — and the sign flips: backpressure on the actor fleet
  means RETIRE servers, an SLO breach on the serving fleet means ADD
  replicas.
- :class:`PromotionController` — closes the canary loop: watches
  per-policy reward and latency series (rewards via ``observe_reward``,
  latency/sheds via the router's exact per-request tap), auto-PROMOTES
  the canary to default on a statistical win (Welch z over the reward
  windows), and auto-ROLLS-BACK on an SLO breach or a statistical loss.
  Both decisions land in the flight recorder with the full input
  snapshot — a promotion in a postmortem always comes with the evidence
  that justified it.
"""

from __future__ import annotations

import collections
import math
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from distributed_ba3c_tpu import telemetry
from distributed_ba3c_tpu.utils import logger, sanitizer
from distributed_ba3c_tpu.utils.concurrency import StoppableThread


class ReplicaSet:
    """Owns the serving replicas' lifecycle behind one router.

    ``factory(idx)`` returns an UNSTARTED predictor for incarnation
    ``idx`` (the factory picks its telemetry role —
    ``predict.router.replica_role`` is the convention); ``warm(pred)``
    optionally precompiles its buckets before it takes traffic;
    ``signals(idx, pred)`` optionally overrides the health source (the
    cross-process http scrape). Replica ids are monotonic (``r<idx>``,
    never reused): a respawned replica must not inherit a corpse's
    telemetry series or outstanding accounting.
    """

    def __init__(
        self,
        router,
        factory: Callable[[int], object],
        min_replicas: int = 1,
        max_replicas: int = 8,
        warm: Optional[Callable[[object], None]] = None,
        signals: Optional[Callable[[int, object], Callable]] = None,
        retire_grace_s: float = 5.0,
    ):
        if not 1 <= min_replicas <= max_replicas:
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas, got "
                f"{min_replicas}/{max_replicas}"
            )
        self.router = router
        self._factory = factory
        self.min_replicas = min_replicas
        self.max_replicas = max_replicas
        self._warm = warm
        self._signals = signals
        self.retire_grace_s = retire_grace_s
        # RLock so the sanitizer's guarded roster can verify the CALLING
        # thread holds it (a plain Lock only knows someone does)
        self._lock = threading.RLock()
        self._next_idx = 0
        self._closed = False
        #: replica ids, spawn order; every shape change is lock-serialized
        #: (BA3C_SANITIZE=1 enforces this at runtime)
        self._live: List[str] = sanitizer.wrap_guarded_list(
            self._lock, "replica_set.live"
        )
        self._flight = telemetry.flight_recorder()
        tele = telemetry.registry("orchestrator")
        self._c_spawns = tele.counter("serving_replica_spawns_total")
        self._c_retires = tele.counter("serving_replica_retires_total")
        self._c_replacements = tele.counter(
            "serving_replica_replacements_total"
        )
        self._c_up = tele.counter("serving_scale_up_total")
        self._c_down = tele.counter("serving_scale_down_total")
        # the corpse sweeper: a DEAD replica (router health verdict) is
        # removed from the set and REPLACED by a fresh incarnation, so a
        # fixed-count deployment heals to its target without an
        # autoscaler in the loop
        self._reconcile_thread = StoppableThread(
            target=self._reconcile_loop, daemon=True,
            name="ReplicaSet-reconcile",
        )

    # -- lifecycle ---------------------------------------------------------
    def start(
        self, n: Optional[int] = None, reconcile_thread: bool = True
    ) -> None:
        """Spawn the initial replicas (default: ``min_replicas``) and
        start the dead-replica reconcile loop. ``reconcile_thread=False``
        leaves the sweeping to an external driver (the topology
        reconciler, orchestrate/reconcile.py, calls :meth:`reconcile`
        from its own tick) — close() handles either mode."""
        n = self.min_replicas if n is None else n
        n = max(self.min_replicas, min(self.max_replicas, n))
        for _ in range(n):
            self._spawn()
        if reconcile_thread:
            self._reconcile_thread.start()

    def close(self) -> None:
        """Stop every replica (teardown; queued tasks get the typed
        ``shutdown`` reject, in-flight dispatches complete). Sets the
        closed flag FIRST so a scale-up tick racing teardown cannot
        register a replica nothing will ever stop."""
        with self._lock:
            self._closed = True
        self._reconcile_thread.stop()
        if self._reconcile_thread.is_alive():
            self._reconcile_thread.join(timeout=5)
        with self._lock:
            live = list(self._live)
            # clear in place, not rebind: rebinding would swap the
            # sanitizer-wrapped roster for a plain list
            del self._live[:]
        for rid in live:
            try:
                pred = self.router.remove_replica(rid)
                pred.stop()
                pred.join(timeout=5)
            except Exception:
                pass

    def _reconcile_loop(self) -> None:
        t = self._reconcile_thread
        while not t.stopped():
            try:
                self.reconcile()
            except Exception:
                logger.exception("replica reconcile failed")
            t._stop_evt.wait(1.0)

    def reconcile(self) -> List[str]:
        """Replace every replica the router has declared DEAD with a
        fresh incarnation (public so tests and the bench drive it
        deterministically). Returns the new replica ids.

        Replacement is heal-to-count, not corpse-keyed 1:1: if a respawn
        RAISES (factory/warmup failure), the corpse is already swept out
        of ``_live`` so the next tick sees no corpse — the shortfall
        against the pre-sweep count (floored at ``min_replicas``) is what
        gets retried every tick until the set actually heals."""
        states = self.router.replica_states()
        with self._lock:
            corpses = [rid for rid in self._live if states.get(rid) == "dead"]
            want = max(len(self._live), self.min_replicas)
            for rid in corpses:
                self._live.remove(rid)
        for rid in corpses:
            try:
                pred = self.router.remove_replica(rid)
                pred.stop()
                pred.join(timeout=5)
            except Exception:
                pass
        replacements: List[str] = []
        while True:
            with self._lock:
                if len(self._live) >= want:
                    break
            try:
                new_rid = self._spawn()
            except Exception:
                # a raising spawn must not lose the slot NOR skip the
                # other corpses' replacements — log and retry next tick
                logger.exception(
                    "serving replica respawn failed — retrying next tick"
                )
                break
            dead = (
                corpses[len(replacements)]
                if len(replacements) < len(corpses) else None
            )
            replacements.append(new_rid)
            self._c_replacements.inc()
            self._flight.record(
                "serving_replica_replace", dead=dead, replacement=new_rid
            )
            logger.warn(
                "serving replica %s dead — replaced by %s", dead, new_rid
            )
        return replacements

    @property
    def target(self) -> int:
        with self._lock:
            return len(self._live)

    def replica_ids(self) -> List[str]:
        with self._lock:
            return list(self._live)

    # -- scaling -----------------------------------------------------------
    def scale_by(self, delta: int, reason: str = "") -> int:
        return self.scale_to(self.target + delta, reason)

    def scale_to(self, n: int, reason: str = "") -> int:
        """Move the replica count to ``n`` (clamped to bounds); grow
        spawns fresh incarnations, shrink retires the youngest first
        (the oldest replicas are the best-warmed). Every actual change
        is counted + flight-recorded."""
        with self._lock:
            if self._closed:
                return len(self._live)  # teardown won: nothing to scale
        n = max(self.min_replicas, min(self.max_replicas, int(n)))
        old = self.target
        if n == old:
            return old
        if n > old:
            for _ in range(n - old):
                self._spawn()
            self._c_up.inc()
            self._flight.record(
                "serving_scale_up", frm=old, to=n, reason=reason[:200]
            )
            logger.info("serving scale up %d -> %d (%s)", old, n, reason)
        else:
            for _ in range(old - n):
                with self._lock:
                    rid = self._live.pop() if self._live else None
                if rid is not None:
                    self._retire(rid)
            self._c_down.inc()
            self._flight.record(
                "serving_scale_down", frm=old, to=n, reason=reason[:200]
            )
            logger.info("serving scale down %d -> %d (%s)", old, n, reason)
        return n

    def _spawn(self) -> str:
        with self._lock:
            if self._closed:
                raise RuntimeError("ReplicaSet is closed")
            idx = self._next_idx
            self._next_idx += 1
        rid = f"r{idx}"
        pred = self._factory(idx)
        pred.start()
        if self._warm is not None:
            self._warm(pred)
        sig = self._signals(idx, pred) if self._signals is not None else None
        self.router.add_replica(rid, pred, signals=sig)
        with self._lock:
            if self._closed:
                born_dead = True
            else:
                born_dead = False
                self._live.append(rid)
        if born_dead:
            # close() swept _live while we were building (factory/warmup
            # can take seconds) and will never revisit this replica —
            # tear it down HERE or its scheduler threads outlive the run
            try:
                self.router.remove_replica(rid)
            except Exception:
                pass
            pred.stop()
            pred.join(timeout=5)
            raise RuntimeError("ReplicaSet closed during spawn")
        self._c_spawns.inc()
        self._flight.record("serving_replica_spawn", replica=rid)
        return rid

    def _retire(self, rid: str) -> None:
        """Out of routing immediately; then a bounded drain grace for its
        outstanding work before stop() (which completes in-flight
        dispatches and sheds anything still queued with the typed
        ``shutdown`` reject — bounded, never a hang)."""
        try:
            pred = self.router.remove_replica(rid)
        except KeyError:
            return
        deadline = time.monotonic() + self.retire_grace_s
        sig = None
        try:
            from distributed_ba3c_tpu.predict.router import replica_signals

            sig = replica_signals(pred)
        except Exception:
            pass
        while sig is not None and time.monotonic() < deadline:
            try:
                s = sig()
                if s.get("queue_depth", 0) <= 0 and s.get("inflight", 0) <= 0:
                    break
            except Exception:
                break
            time.sleep(0.05)
        pred.stop()
        try:
            pred.join(timeout=5)
        except Exception:
            pass
        self._c_retires.inc()
        self._flight.record("serving_replica_retire", replica=rid)


class ServingScalerPolicy:
    """The pure serving-scale decision (unit-testable without a plane).

    Watermarks on the routed plane's WINDOWED signals
    (``router.aggregate_signals``): served p99 vs the SLO and the
    shed-rate delta. Bang-bang with the PR-7 hysteresis kit — patience
    consecutive ticks, post-decision cooldown — because a replica move
    costs a spawn + warmup, so the loop must be stable against one noisy
    tick. Sign convention (opposite the fleet autoscaler's): pressure
    ADDS replicas.
    """

    def __init__(
        self,
        slo_ms: float,
        p99_high_frac: float = 0.9,
        p99_low_frac: float = 0.4,
        shed_high: float = 0.01,
        patience: int = 2,
        cooldown_ticks: int = 3,
        step: int = 1,
    ):
        if slo_ms <= 0:
            raise ValueError(f"slo_ms must be > 0, got {slo_ms}")
        if not 0 <= p99_low_frac < p99_high_frac:
            raise ValueError(
                f"need 0 <= p99_low_frac < p99_high_frac, got "
                f"{p99_low_frac}/{p99_high_frac}"
            )
        self.slo_ms = slo_ms
        self.p99_high_frac = p99_high_frac
        self.p99_low_frac = p99_low_frac
        self.shed_high = shed_high
        self.patience = max(1, patience)
        self.cooldown_ticks = max(0, cooldown_ticks)
        self.step = max(1, step)
        self._pressured = 0
        self._relaxed = 0
        self._cooldown = 0

    def decide(self, s: Dict[str, float]) -> Tuple[int, str]:
        if self._cooldown > 0:
            self._cooldown -= 1
            return 0, ""
        p99 = s.get("served_p99_ms")
        shed = float(s.get("shed_rate", 0.0) or 0.0)
        outstanding = float(s.get("outstanding_rows", 0.0) or 0.0)
        pressured = shed > self.shed_high or (
            p99 is not None and p99 >= self.p99_high_frac * self.slo_ms
        )
        # relaxed: comfortably inside the SLO with zero shedding — or a
        # provably idle window (no samples AND nothing outstanding).
        # p99 unknown with work outstanding is INDETERMINATE, not idle.
        relaxed = not pressured and shed <= 0 and (
            (p99 is not None and p99 <= self.p99_low_frac * self.slo_ms)
            or (p99 is None and outstanding <= 0)
        )
        if pressured:
            self._pressured += 1
            self._relaxed = 0
        elif relaxed:
            self._relaxed += 1
            self._pressured = 0
        else:
            self._pressured = self._relaxed = 0
        if self._pressured >= self.patience:
            self._pressured = self._relaxed = 0
            self._cooldown = self.cooldown_ticks
            return self.step, (
                f"SLO pressure: served p99 "
                f"{'n/a' if p99 is None else format(p99, '.1f')} ms vs "
                f"{self.slo_ms} ms SLO, shed rate {shed:.2%} — add serving "
                "capacity"
            )
        if self._relaxed >= self.patience:
            self._pressured = self._relaxed = 0
            self._cooldown = self.cooldown_ticks
            return -self.step, (
                f"SLO slack: served p99 "
                f"{'n/a' if p99 is None else format(p99, '.1f')} ms well "
                f"inside {self.slo_ms} ms with zero shed — retire a replica"
            )
        return 0, ""


class ReplicaAutoscaler(StoppableThread):
    """scrape router aggregate → decide → ``replica_set.scale_by`` (the
    PR-7 Autoscaler loop, serving edition); every decision is counted and
    flight-recorded with its input snapshot."""

    def __init__(
        self,
        replica_set: ReplicaSet,
        policy: ServingScalerPolicy,
        interval_s: float = 2.0,
    ):
        super().__init__(daemon=True, name="ReplicaAutoscaler")
        self.replica_set = replica_set
        self.policy = policy
        self.interval_s = interval_s
        self._flight = telemetry.flight_recorder()
        tele = telemetry.registry("orchestrator")
        self._c_ticks = tele.counter("serving_autoscale_ticks_total")
        self._c_decisions = tele.counter("serving_autoscale_decisions_total")

    def run(self) -> None:
        while not self.stopped():
            try:
                self.tick()
            except Exception:
                # one raising tick (e.g. a replica factory failing mid
                # scale-up) must not kill the control loop for the run
                logger.exception("serving autoscale tick failed")
            self._stop_evt.wait(self.interval_s)

    def tick(self) -> None:
        self._c_ticks.inc()
        s = self.replica_set.router.aggregate_signals()
        delta, reason = self.policy.decide(s)
        if delta == 0:
            return
        old = self.replica_set.target
        new = self.replica_set.scale_by(delta, reason=reason)
        if new == old:
            return  # clamped at a bound — no decision to record
        self._c_decisions.inc()
        self._flight.record(
            "serving_scale_decision",
            delta=delta, frm=old, to=new, reason=reason[:200],
            served_p99_ms=s.get("served_p99_ms"),
            shed_rate=s.get("shed_rate"),
            replicas_live=s.get("replicas_live"),
        )


def welch_z(
    a: "collections.deque", b: "collections.deque"
) -> Optional[float]:
    """Welch z-statistic for mean(a) - mean(b) (the promotion test's
    effect direction: positive = a wins). None when either window is
    empty or both variances are zero with equal means (no evidence)."""
    na, nb = len(a), len(b)
    if na < 2 or nb < 2:
        return None
    ma = sum(a) / na
    mb = sum(b) / nb
    va = sum((x - ma) ** 2 for x in a) / (na - 1)
    vb = sum((x - mb) ** 2 for x in b) / (nb - 1)
    denom = math.sqrt(va / na + vb / nb)
    if denom == 0:
        if ma == mb:
            return None
        return math.inf if ma > mb else -math.inf
    return (ma - mb) / denom


class PromotionController(StoppableThread):
    """The automated canary loop over a serving router.

    ``start_canary(params)`` makes the candidate hot on every replica and
    routes ``fraction`` of traffic to it; from then on each ``tick()``
    (public — tests and the bench drive it deterministically):

    - **rolls back** when the canary breaches the serving SLO (windowed
      per-policy p99 from the router's exact latency tap > ``slo_ms``, or
      its shed rate > ``breach_shed_rate``, judged only after
      ``min_decide_tasks`` of its traffic) or statistically LOSES on
      reward (Welch z <= -z_promote);
    - **promotes** when the canary statistically WINS on reward (both
      reward windows >= ``min_samples``, Welch z >= ``z_promote``) while
      inside the SLO: ``router.promote`` republishes the canary params as
      default everywhere and clears the split.

    Reward samples arrive via ``observe_reward(policy, value)`` — the
    caller decides what "reward" is (episode score attributed to the
    serving policy; the bench feeds per-policy score streams). Both
    decisions are flight-recorded WITH the full input snapshot.
    """

    IDLE, WATCHING, PROMOTED, ROLLED_BACK = (
        "idle", "watching", "promoted", "rolled_back"
    )

    def __init__(
        self,
        router,
        canary_policy: str = "canary",
        fraction: float = 0.1,
        slo_ms: float = 50.0,
        min_samples: int = 30,
        z_promote: float = 1.96,
        breach_shed_rate: float = 0.05,
        min_decide_tasks: int = 20,
        window: int = 512,
        interval_s: float = 2.0,
    ):
        super().__init__(daemon=True, name="PromotionController")
        if not 0 < fraction <= 1:
            raise ValueError(f"canary fraction {fraction} not in (0, 1]")
        self.router = router
        self.canary_policy = canary_policy
        self.fraction = fraction
        self.slo_ms = slo_ms
        self.min_samples = max(2, min_samples)
        self.z_promote = z_promote
        self.breach_shed_rate = breach_shed_rate
        self.min_decide_tasks = min_decide_tasks
        self.interval_s = interval_s
        self.state = self.IDLE
        self._lock = threading.Lock()
        self._window = window
        self._rewards: Dict[str, collections.deque] = {}
        self._lats: Dict[str, collections.deque] = {}
        self._served: Dict[str, int] = {}
        self._sheds: Dict[str, int] = {}
        self._flight = telemetry.flight_recorder()
        tele = telemetry.registry("orchestrator")
        self._c_ticks = tele.counter("promotion_ticks_total")
        self._c_promotions = tele.counter("canary_promotions_total")
        self._c_rollbacks = tele.counter("canary_rollbacks_total")
        self._g_state = tele.gauge("promotion_state")
        self._g_state.set(0.0)
        # the router's exact per-request feed: latency samples + typed
        # sheds, attributed to the policy the ROUTER routed
        router.latency_tap = self._tap

    # -- sample feeds ------------------------------------------------------
    def _tap(self, policy: str, latency_s, shed_reason) -> None:
        with self._lock:
            if latency_s is None:
                self._sheds[policy] = self._sheds.get(policy, 0) + 1
                return
            self._served[policy] = self._served.get(policy, 0) + 1
            dq = self._lats.get(policy)
            if dq is None:
                self._lats[policy] = dq = collections.deque(
                    maxlen=self._window
                )
            dq.append(latency_s)

    def observe_reward(self, policy: str, value: float) -> None:
        with self._lock:
            dq = self._rewards.get(policy)
            if dq is None:
                self._rewards[policy] = dq = collections.deque(
                    maxlen=self._window
                )
            dq.append(float(value))

    # -- the canary lifecycle ----------------------------------------------
    def start_canary(self, params) -> None:
        """Candidate goes live on ``fraction`` of traffic; evidence
        windows reset so a previous canary's record cannot vouch for (or
        damn) this one."""
        self.router.add_policy(self.canary_policy, params)
        with self._lock:
            self._rewards.clear()
            self._lats.clear()
            self._served.clear()
            self._sheds.clear()
        self.router.set_canary(self.canary_policy, self.fraction)
        self.state = self.WATCHING
        self._g_state.set(1.0)
        self._flight.record(
            "canary_start", policy=self.canary_policy,
            fraction=self.fraction, slo_ms=self.slo_ms,
        )
        logger.info(
            "canary %s live on %.1f%% of traffic",
            self.canary_policy, 100 * self.fraction,
        )

    def _p99_ms(self, policy: str) -> Optional[float]:
        dq = self._lats.get(policy)
        if not dq:
            return None
        xs = sorted(dq)
        return xs[min(len(xs) - 1, int(0.99 * len(xs)))] * 1000.0

    def snapshot(self) -> Dict[str, object]:
        """The decision inputs, exactly as the next tick would read them
        (and exactly what rides into the flight record)."""
        with self._lock:
            c, d = self.canary_policy, "default"
            rc = self._rewards.get(c, ())
            rd = self._rewards.get(d, ())
            served_c = self._served.get(c, 0)
            sheds_c = self._sheds.get(c, 0)
            z = welch_z(
                self._rewards.get(c, collections.deque()),
                self._rewards.get(d, collections.deque()),
            )
            tasks_c = served_c + sheds_c
            return {
                "canary": c,
                "fraction": self.fraction,
                "slo_ms": self.slo_ms,
                "reward_n_canary": len(rc),
                "reward_n_default": len(rd),
                "reward_mean_canary": (
                    sum(rc) / len(rc) if rc else None
                ),
                "reward_mean_default": (
                    sum(rd) / len(rd) if rd else None
                ),
                "welch_z": z,
                "canary_tasks": tasks_c,
                "canary_sheds": sheds_c,
                "canary_shed_rate": (
                    sheds_c / tasks_c if tasks_c else 0.0
                ),
                "canary_p99_ms": self._p99_ms(c),
                "default_p99_ms": self._p99_ms(d),
            }

    def run(self) -> None:
        while not self.stopped():
            try:
                self.tick()
            except Exception:
                # a raising tick must not kill the canary watch loop —
                # an unwatched canary would serve its split forever
                logger.exception("promotion controller tick failed")
            self._stop_evt.wait(self.interval_s)

    def tick(self) -> None:
        if self.state != self.WATCHING:
            return
        self._c_ticks.inc()
        s = self.snapshot()
        # SLO breach first: a canary that hurts users rolls back NOW,
        # whatever its reward says
        if s["canary_tasks"] >= self.min_decide_tasks and (
            s["canary_shed_rate"] > self.breach_shed_rate
            or (
                s["canary_p99_ms"] is not None
                and s["canary_p99_ms"] > self.slo_ms
            )
        ):
            self._rollback("slo_breach", s)
            return
        z = s["welch_z"]
        enough = (
            s["reward_n_canary"] >= self.min_samples
            and s["reward_n_default"] >= self.min_samples
        )
        if enough and z is not None and z <= -self.z_promote:
            self._rollback("reward_loss", s)
        elif enough and z is not None and z >= self.z_promote:
            # a reward win alone cannot promote: an external reward feed
            # can outrun routed traffic, and below min_decide_tasks the
            # breach check above never ran — so promotion also requires
            # the canary's OWN serving evidence (which, having passed the
            # breach-first check, is inside the SLO)
            if (
                s["canary_tasks"] >= self.min_decide_tasks
                and s["canary_p99_ms"] is not None
            ):
                self._promote(s)

    def _promote(self, s: Dict[str, object]) -> None:
        self.router.promote(self.canary_policy)
        self.state = self.PROMOTED
        self._g_state.set(2.0)
        self._c_promotions.inc()
        self._flight.record("canary_promote", **s)
        logger.info(
            "canary %s PROMOTED to default (z=%.2f over %d/%d reward "
            "samples)", self.canary_policy, s["welch_z"],
            s["reward_n_canary"], s["reward_n_default"],
        )

    def _rollback(self, why: str, s: Dict[str, object]) -> None:
        self.router.set_canary(None)
        self.state = self.ROLLED_BACK
        self._g_state.set(3.0)
        self._c_rollbacks.inc()
        self._flight.record("canary_rollback", why=why, **s)
        logger.warn(
            "canary %s ROLLED BACK (%s): p99=%s ms shed=%.2f%% z=%s",
            self.canary_policy, why, s["canary_p99_ms"],
            100 * s["canary_shed_rate"], s["welch_z"],
        )
