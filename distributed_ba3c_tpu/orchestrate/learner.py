"""Learner checkpoint-failover: ``run_with_resume.sh`` promoted into the
supervisor, with the resume counted and postmortem-dumped.

The shell launcher (scripts/run_with_resume.sh) already had the right
semantics — relaunch a dead trainer with ``--load`` whenever a FINALIZED
checkpoint exists, never resume from an empty dir, give startup extra
stall grace — but it was invisible to the telemetry plane: a failover left
no counter, no flight event, no dump. This class is the same loop as a
supervised component: a SIGKILLed learner resumes from the last finalized
checkpoint without operator action, and the resume is accounted as
``tele/orchestrator/learner_*`` series plus a ``learner_failover`` flight
event (docs/orchestration.md).

Entry point: ``python -m distributed_ba3c_tpu.orchestrate`` (the shell
script stays for bare-metal compat and points here).
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
from typing import List, Optional

from distributed_ba3c_tpu import telemetry
from distributed_ba3c_tpu.utils import logger

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def finalized_step(ckpt_dir: str) -> Optional[int]:
    """The last FINALIZED checkpoint step, or None.

    The resume gate is checkpoint.json's non-null ``latest`` — written
    only after the save's wait_until_finished — NOT the directory's
    existence: CheckpointManager creates the dir at startup, so a crash
    before the first save must not make every retry ``--load`` an empty
    dir and burn the restart budget on a run that never trained (same
    gate as run_with_resume.sh / launch_multihost.sh).
    """
    meta = os.path.join(ckpt_dir, "checkpoint.json")
    try:
        with open(meta) as fh:
            latest = json.load(fh).get("latest")
        return int(latest) if latest is not None else None
    except (OSError, ValueError, TypeError):
        return None


class LearnerSupervisor:
    """Run the learner as a supervised child; resume it from the last
    finalized checkpoint when it dies.

    ``train_args`` are train.py's arguments and must include ``--logdir
    <logdir>`` but NOT ``--load`` — the supervisor adds ``--load
    <logdir>/checkpoints`` whenever a finalized checkpoint exists, so
    re-running the same command over a prior run's logdir RESUMES it.

    ``stall_secs > 0`` adds the shell launcher's stall watchdog: no
    ``log.log`` mtime progress for that long kills the process GROUP
    (the trainer owns its session via ``start_new_session``) and lets the
    resume path take over. Startup gets ``startup_grace_s`` extra until
    the attempt's first log write (XLA compile + pool claim).
    """

    def __init__(
        self,
        logdir: str,
        train_args: List[str],
        max_restarts: int = 5,
        stall_secs: float = 0.0,
        startup_grace_s: float = 600.0,
        train_py: Optional[str] = None,
        python: Optional[str] = None,
        poll_s: float = 1.0,
    ):
        self.logdir = logdir
        self.ckpt_dir = os.path.join(logdir, "checkpoints")
        self.train_args = list(train_args)
        if "--load" in self.train_args:
            raise ValueError(
                "--load belongs to the supervisor: it is added automatically "
                "whenever a finalized checkpoint exists in the run's logdir"
            )
        # the stall watchdog stats <logdir>/log.log and the resume gate
        # reads <logdir>/checkpoints — a train_args --logdir pointing
        # elsewhere would make the supervisor kill a healthy learner on
        # phantom stalls and resume from a directory the child never
        # writes. Catch the typo at construction, like --load above.
        if "--logdir" in self.train_args:
            child_logdir = self.train_args[
                self.train_args.index("--logdir") + 1
            ]
            if os.path.abspath(child_logdir) != os.path.abspath(logdir):
                raise ValueError(
                    f"train args --logdir {child_logdir!r} does not match "
                    f"the supervisor's logdir {logdir!r} — the watchdog "
                    "and the resume gate both read the supervisor's path"
                )
        else:
            raise ValueError(
                "train args must include --logdir (matching the "
                "supervisor's) — train.py's default logdir would desync "
                "the stall watchdog and the resume gate"
            )
        self.max_restarts = max_restarts
        self.stall_secs = stall_secs
        self.startup_grace_s = startup_grace_s
        self.train_py = train_py or os.path.join(_REPO_ROOT, "train.py")
        self.python = python or sys.executable
        self.poll_s = poll_s
        self.attempt = 0
        self.child_pid: Optional[int] = None  # the live attempt's pid
        self._child: Optional[subprocess.Popen] = None
        self._start_mono = 0.0
        self._start_wall = 0.0
        self._stall_killed = False
        self._flight = telemetry.flight_recorder()
        tele = telemetry.registry("orchestrator")
        self._c_restarts = tele.counter("learner_restarts_total")
        self._c_resumes = tele.counter("learner_resumes_total")
        self._g_attempt = tele.gauge("learner_attempt")

    # -- non-blocking attempt primitives -----------------------------------
    # The blocking run() below and the reconciler's LearnerResource
    # (orchestrate/reconcile.py) are the SAME state machine: these
    # primitives are its only implementation, so failover accounting
    # cannot drift between the two drivers.

    def start_attempt(self) -> None:
        """Launch the next attempt through the resume gate (``--load``
        exactly when a finalized checkpoint exists). No-op while an
        attempt is live."""
        if self.attempt_running():
            return
        args = list(self.train_args)
        if finalized_step(self.ckpt_dir) is not None:
            args += ["--load", self.ckpt_dir]
        self._g_attempt.set(self.attempt)
        logger.info(
            "[learner supervisor] attempt %d: %s %s %s",
            self.attempt, self.python, self.train_py, " ".join(args),
        )
        # own session/process group: a stall kill must reap the trainer
        # AND its spawned children (env servers, simulators) without
        # touching unrelated processes
        child = subprocess.Popen(
            [self.python, self.train_py] + args, start_new_session=True
        )
        self._child = child
        self.child_pid = child.pid
        self._start_mono = time.monotonic()
        # wall clock on purpose: stall progress is the log FILE's st_mtime,
        # which only compares against wall time
        self._start_wall = time.time()  # ba3clint: disable=A4
        self._stall_killed = False

    def attempt_running(self) -> bool:
        return self._child is not None and self._child.poll() is None

    def attempt_stalled(self) -> bool:
        """The stall watchdog's verdict on the LIVE attempt (always
        False with the watchdog disabled or no attempt running)."""
        if self.stall_secs <= 0 or not self.attempt_running():
            return False
        return self._stalled(
            os.path.join(self.logdir, "log.log"), self._start_wall
        )

    def kill_attempt(self, reason: str = "stall") -> None:
        """Kill the live attempt's process group (stall recovery); the
        next :meth:`reap_attempt` reports it as a non-zero exit so the
        resume path takes over."""
        child = self._child
        if child is None or child.poll() is not None:
            return
        age = time.monotonic() - self._start_mono
        logger.warn(
            "[learner supervisor] %s after %.0fs — killing group %d",
            reason, age, child.pid,
        )
        self._flight.record(
            "learner_stall_kill", pid=child.pid, age_s=round(age, 1)
        )
        self._stall_killed = True
        self._kill_group(child)
        child.wait()

    def reap_attempt(self) -> Optional[int]:
        """The attempt's exit code once it has exited (reaping it), else
        None. A stall-killed attempt reports at least 1 even if the
        group died with rc 0."""
        child = self._child
        if child is None:
            return None
        rc = child.poll()
        if rc is None:
            return None
        self._child = None
        self.child_pid = None
        if self._stall_killed:
            rc = rc or 1
        return rc

    def note_exit(self, rc: int) -> str:
        """Account one attempt's exit: ``"done"`` (clean finish),
        ``"retry"`` (failover armed — counters bumped, flight event +
        dump written), or ``"giveup"`` (restart budget exhausted)."""
        if rc == 0:
            logger.info(
                "learner finished cleanly after %d restart(s)", self.attempt
            )
            return "done"
        self.attempt += 1
        if self.attempt > self.max_restarts:
            logger.error(
                "learner giving up after %d restarts (rc=%s)",
                self.max_restarts, rc,
            )
            self._flight.record(
                "learner_giveup", rc=rc, attempts=self.attempt
            )
            self._flight.dump("learner restart budget exhausted")
            return "giveup"
        step = finalized_step(self.ckpt_dir)
        self._c_restarts.inc()
        if step is not None:
            self._c_resumes.inc()
        # the failover IS the postmortem moment: the next operator to
        # look must find on disk that the learner died with rc=<x> and
        # resumed from step <y> — without having watched the console
        self._flight.record(
            "learner_failover",
            rc=rc,
            attempt=self.attempt,
            resume_step=step,
        )
        self._flight.dump("learner failover")
        logger.warn(
            "learner died (rc=%s) — attempt %d/%d %s",
            rc, self.attempt, self.max_restarts,
            f"resuming from finalized step {step}"
            if step is not None
            else "restarting from scratch (no finalized checkpoint)",
        )
        return "retry"

    def terminate_attempt(self) -> None:
        """Teardown: kill and reap the live attempt, if any
        (idempotent)."""
        child = self._child
        self._child = None
        self.child_pid = None
        if child is not None and child.poll() is None:
            self._kill_group(child)
            child.wait()

    def run(self) -> int:
        """Blocking supervision loop; returns the final exit code (0 =
        the learner finished cleanly, possibly across several resumes)."""
        try:
            self.start_attempt()
            while True:
                rc = self.reap_attempt()
                if rc is not None:
                    verdict = self.note_exit(rc)
                    if verdict == "done":
                        return 0
                    if verdict == "giveup":
                        return rc
                    self.start_attempt()
                elif self.attempt_stalled():
                    self.kill_attempt()
                else:
                    time.sleep(self.poll_s)
        finally:
            self.terminate_attempt()

    def _stalled(self, log_path: str, attempt_start_wall: float) -> bool:
        """The shell watchdog's rule: progress = the run log's mtime;
        measured against max(attempt start, log mtime) so a stale log from
        a PREVIOUS attempt cannot kill this one, and until this attempt's
        first write the threshold gets the startup grace."""
        last = attempt_start_wall
        thresh = self.stall_secs + self.startup_grace_s
        try:
            m = os.stat(log_path).st_mtime
            if m > last:
                last = m
                thresh = self.stall_secs
        except OSError:
            pass
        # wall arithmetic is forced by st_mtime above; an NTP step can at
        # worst delay or hasten ONE stall kill, never corrupt training
        return time.time() - last > thresh  # ba3clint: disable=A4

    @staticmethod
    def _kill_group(child: subprocess.Popen) -> None:
        try:
            os.killpg(child.pid, signal.SIGTERM)
        except (OSError, ProcessLookupError):
            pass
        deadline = time.monotonic() + 5.0
        while child.poll() is None and time.monotonic() < deadline:
            time.sleep(0.1)
        if child.poll() is None:
            try:
                os.killpg(child.pid, signal.SIGKILL)
            except (OSError, ProcessLookupError):
                pass
