"""FleetSupervisor: the env-server fleet's lifecycle, owned end-to-end.

The PR-4 actor plane *survives* failure (the master prunes silent clients,
resets incarnations, drops ring-refusing speakers) and the PR-5 telemetry
plane *measures* it (prune counters, flight-recorder postmortems, fleet
piggyback). What neither does is ACT: a SIGKILLed env server stayed dead
until an operator noticed, and fleet size was fixed at launch
(scripts/launch_env_fleet.py walked away after spawning). The supervisor
closes that loop:

- **spawn** every server slot from a declarative :class:`FleetSpec`
  (orchestrate/spec.py), via a pluggable factory so C++ block servers,
  python SimulatorProcesses and test fakes all ride the same lifecycle;
- **detect** death two ways — the process table (a crashed child) and the
  MASTER'S telemetry account (a ``prune`` flight-recorder event for a
  slot whose process is still alive means the server is wedged: the
  master gave up on it after ``actor_timeout`` of silence). The
  supervisor keeps no duplicate heartbeat plane of its own;
- **respawn** with per-slot exponential backoff and a fleet-wide
  restart-budget circuit breaker (a crash loop must degrade into a
  visible incident, not a fork storm), reclaiming stale /dev/shm rings
  before each block-shm spawn;
- **scale** between ``fleet_min``/``fleet_max`` on demand
  (:meth:`scale_to`, driven by orchestrate/autoscaler.py), retiring the
  highest slots first;
- **account** everything as ``tele/orchestrator/*`` series and
  flight-recorder events, so every scale/respawn decision is visible on
  the scrape endpoint and in postmortems (docs/orchestration.md).

The supervisor satisfies the StartProcOrThread protocol
(start/stop/join/close), so cli.py appends it to the startables list in
place of a bare process list.
"""

from __future__ import annotations

import collections
import multiprocessing as mp
import os
import signal
import threading
import time
import weakref
from typing import Callable, Dict, List, Optional, Tuple

from distributed_ba3c_tpu import telemetry
from distributed_ba3c_tpu.orchestrate.spec import FleetSpec
from distributed_ba3c_tpu.utils import logger
from distributed_ba3c_tpu.utils.concurrency import (
    StoppableThread,
    ensure_proc_terminate,
    start_proc_mask_signal,
)

#: ident-repr delimiters that may legally follow a slot's ident prefix
#: (``cppsim-3*block``, ``cppsim-3-7``, ``simulator-2``) — a prefix match
#: NOT followed by one of these is a longer index (cppsim-30 vs cppsim-3)
_IDENT_DELIMS = ("'", '"', "*", "-")


class _Slot:
    """One server slot: the process currently (or about to be) filling it
    plus its failure bookkeeping. Slot index — not pid — is the stable
    identity: the wire ident and the shm ring name derive from it, which
    is what makes a respawn land as an incarnation reset instead of a
    brand-new client."""

    __slots__ = (
        "idx", "proc", "started_t", "failures", "next_spawn_t",
        "ever_started",
    )

    def __init__(self, idx: int):
        self.idx = idx
        self.proc = None
        self.started_t = 0.0
        self.failures = 0
        self.next_spawn_t = 0.0  # monotonic; 0 = spawn at next tick
        self.ever_started = False


def default_factory(
    spec: FleetSpec, total_envs: Optional[int] = None
) -> Callable[[int], object]:
    """Factory building the spec's C++ env servers (one per slot).

    ``total_envs`` keeps CLI compat with env-count-shaped configs
    (``--n_envs``/``--simulator_procs`` need not divide
    ``envs_per_server``): the slot covering the remainder hosts fewer
    envs; slots GROWN past the initial fleet host the full block.
    """

    def build(slot_idx: int):
        from distributed_ba3c_tpu.envs import native

        n = spec.envs_per_server
        if total_envs is not None:
            remaining = total_envs - slot_idx * spec.envs_per_server
            if 0 < remaining < n:
                n = remaining
        return native.CppEnvServerProcess(
            spec.base_idx + slot_idx,
            spec.pipe_c2s,
            spec.pipe_s2c,
            game=spec.game,
            n_envs=n,
            frame_history=spec.frame_history,
            wire=spec.wire,
            shm_ring_cap=spec.shm_ring_cap,
        )

    return build


class FleetSupervisor(StoppableThread):
    """Supervise one fleet of env-server processes per the spec.

    ``factory(slot_idx)`` returns an UNSTARTED process-like object
    (``start/is_alive/terminate/kill/join``, optional ``pid``/``exitcode``)
    — a fresh object per call, since a multiprocessing.Process cannot be
    restarted. ``ident_prefix(slot_idx)`` names the slot's wire-identity
    prefix (default: the C++ servers' ``cppsim-<base+idx>``), used to map
    the master's prune events back to slots.
    """

    def __init__(
        self,
        spec: FleetSpec,
        factory: Optional[Callable[[int], object]] = None,
        ident_prefix: Optional[Callable[[int], str]] = None,
        poll_interval_s: float = 0.25,
    ):
        super().__init__(daemon=True, name="FleetSupervisor")
        self.spec = spec
        self._factory = factory or default_factory(spec)
        self._ident_prefix = ident_prefix or (
            lambda i: f"cppsim-{spec.base_idx + i}"
        )
        self.poll_interval_s = poll_interval_s
        # one lock over slot-table structure: ticks, scale ops and chaos
        # kills come from different threads, none of them hot
        self._lock = threading.RLock()
        self._slots: Dict[int, _Slot] = {}
        # retired-but-not-yet-reaped processes: (slot_idx, proc, kill_at).
        # scale_to only TERMINATES; the tick reaps, escalating to SIGKILL
        # after a grace — a slow-exiting server must not linger as a
        # zombie (or still hold its slot's wire identity when the slot is
        # re-grown; ROUTER_HANDOVER would flip replies between two live
        # servers)
        self._retired: List[tuple] = []
        self._target = spec.fleet_size
        self._respawn_times: collections.deque = collections.deque()
        self._circuit_open = spec.restart_budget == 0
        self._fleet_started = False

        self._flight = telemetry.flight_recorder()
        # wedge detection reads the master's prune stream from the flight
        # ring; only events recorded after OUR start matter
        self._events_after = time.monotonic()

        tele = telemetry.registry("orchestrator")
        self._c_spawns = tele.counter("server_spawns_total")
        self._c_respawns = tele.counter("server_respawns_total")
        self._c_deaths = tele.counter("server_deaths_total")
        self._c_wedged = tele.counter("wedged_kills_total")
        self._c_scale_up = tele.counter("scale_up_total")
        self._c_scale_down = tele.counter("scale_down_total")
        self._c_circuit = tele.counter("circuit_trips_total")
        self._c_rings = tele.counter("rings_reclaimed_total")
        # the scaled-down-on-purpose vs lost-half-the-fleet distinction
        # lives in this gauge PAIR: target is what the orchestrator wants,
        # live is what the process table shows. target == live == small is
        # a deliberate scale-down; target >> live is an incident.
        ref = weakref.ref(self)
        tele.gauge(
            "fleet_target_size", fn=lambda: s._target if (s := ref()) else 0
        )
        tele.gauge(
            "fleet_live_size",
            fn=lambda: s.live_count() if (s := ref()) else 0,
        )
        tele.gauge(
            "circuit_open",
            fn=lambda: int(s._circuit_open) if (s := ref()) else 0,
        )

    # -- lifecycle (StartProcOrThread protocol) ----------------------------
    def spawn_initial(self) -> None:
        """Spawn the initial fleet (idempotent). Split out of
        :meth:`start` so a reconciler can bring the fleet up without
        starting the supervisor's own thread (orchestrate/reconcile.py
        owns the tick in that mode)."""
        with self._lock:
            if not self._fleet_started:
                self._fleet_started = True
                for i in range(self._target):
                    self._slots[i] = _Slot(i)
                    self._spawn(self._slots[i])

    def start(self) -> None:
        """Spawn the initial fleet, then start the supervision loop."""
        self.spawn_initial()
        super().start()
        logger.info(
            "fleet supervisor up: %d/%d servers (bounds [%d, %d], wire %s)",
            self.live_count(), self._target,
            self.spec.fleet_min, self.spec.fleet_max, self.spec.wire,
        )

    def run(self) -> None:
        while not self.stopped():
            try:
                self._tick()
            except Exception:
                # the supervision loop is the component that must not die
                # of one bad tick — log and keep supervising
                logger.exception("fleet supervisor tick failed")
            self._stop_evt.wait(self.poll_interval_s)

    def join(self, timeout: Optional[float] = None) -> None:
        if self.is_alive():
            super().join(timeout)

    def close(self) -> None:
        """Terminate and reap every child (idempotent) — including
        scale-down retirees the tick has not reaped yet."""
        self.stop()
        with self._lock:
            procs = [s.proc for s in self._slots.values() if s.proc is not None]
            procs += [p for _, p, _ in self._retired]
            self._retired = []
        for p in procs:
            try:
                if p.is_alive():
                    p.terminate()
            except Exception:
                pass
        for p in procs:
            try:
                p.join(timeout=5)
                if p.is_alive():
                    p.kill()
                    p.join(timeout=5)
            except Exception:
                pass

    # -- introspection -----------------------------------------------------
    def observe(self) -> Dict[str, object]:
        """Read-only snapshot of desired vs live, in the Reconcilable
        protocol's shape (orchestrate/reconcile.py): slot liveness from
        the process table, wedge suspects from the master's prune stream
        (peeked — the cursor is only consumed by the tick that acts).
        Dead-but-unreaped slots report as due: the reap IS the pending
        action."""
        now = time.monotonic()
        wedged = self._wedge_suspects()
        with self._lock:
            retired_idxs = {idx for idx, _, _ in self._retired}
            live: List[int] = []
            due: List[int] = []
            backoff: List[int] = []
            for slot in sorted(self._slots.values(), key=lambda s: s.idx):
                p = slot.proc
                if p is not None and p.is_alive():
                    live.append(slot.idx)
                elif p is not None:
                    due.append(slot.idx)  # dead, reap pending
                elif slot.idx in retired_idxs or now < slot.next_spawn_t:
                    backoff.append(slot.idx)
                else:
                    due.append(slot.idx)
            return {
                "kind": "fleet",
                "target": self._target,
                "live": tuple(live),
                "vacant_due": tuple(due),
                "vacant_backoff": tuple(backoff),
                "retiring": tuple(sorted(retired_idxs)),
                "wedged": tuple(wedged),
                "circuit_open": self._circuit_open,
                "ever_started": self._fleet_started,
            }

    def _wedge_suspects(self) -> List[int]:
        """Slots the master has pruned whose process is still alive —
        the same verdict :meth:`_kill_wedged` acts on, WITHOUT advancing
        the event cursor or killing anything."""
        events = self._flight.events_since(self._events_after, kind="prune")
        out = set()
        for t, _, fields in events:
            ident_repr = str(fields.get("ident", ""))
            with self._lock:
                idx = self._slot_for_ident(ident_repr)
                slot = self._slots.get(idx) if idx is not None else None
                proc = slot.proc if slot is not None else None
                stale = slot is None or t <= slot.started_t
            if proc is not None and not stale and proc.is_alive():
                out.add(slot.idx)
        return sorted(out)

    def tick(self) -> None:
        """One full supervision pass, caller-driven (the reconciler's
        ``act``). Identical to one iteration of :meth:`run`."""
        self._tick()

    def live_count(self) -> int:
        with self._lock:
            return sum(
                1
                for s in self._slots.values()
                if s.proc is not None and s.proc.is_alive()
            )

    @property
    def target(self) -> int:
        return self._target

    @property
    def circuit_open(self) -> bool:
        return self._circuit_open

    def live_slots(self) -> List[Tuple[int, object]]:
        """``[(slot_idx, proc)]`` for currently-alive slots (chaos
        injection picks its victims here)."""
        with self._lock:
            return [
                (s.idx, s.proc)
                for s in sorted(self._slots.values(), key=lambda x: x.idx)
                if s.proc is not None and s.proc.is_alive()
            ]

    def sigkill_slot(self, idx: int) -> bool:
        """SIGKILL a slot's process (chaos harness / tests): no goodbye on
        the wire, exactly like an OOM kill. Returns False if not alive."""
        with self._lock:
            slot = self._slots.get(idx)
            proc = slot.proc if slot is not None else None
        if proc is None or not proc.is_alive():
            return False
        pid = getattr(proc, "pid", None)
        if pid:
            os.kill(pid, signal.SIGKILL)
        else:  # duck-typed test fakes have no real pid
            proc.kill()
        return True

    # -- scaling -----------------------------------------------------------
    def scale_by(self, delta: int, reason: str = "") -> int:
        return self.scale_to(self._target + delta, reason)

    def scale_to(self, n: int, reason: str = "") -> int:
        """Move the fleet target to ``n`` (clamped to the spec bounds);
        returns the new target. Growth adds fresh slots (spawned by the
        next tick); shrink retires the highest slots immediately. Every
        ACTUAL change is counted and flight-recorded — scale decisions
        must be postmortem-visible."""
        spec = self.spec
        n = max(spec.fleet_min, min(spec.fleet_max, int(n)))
        with self._lock:
            old = self._target
            if n == old:
                return old
            self._target = n
            if n > old:
                for i in range(old, n):
                    # slot indices are dense 0..target-1: a retired slot's
                    # index (and thus wire ident + ring name) is reused by
                    # the next growth, keeping ring files bounded by
                    # fleet_max ever existing
                    if i not in self._slots:
                        self._slots[i] = _Slot(i)
                self._c_scale_up.inc()
                self._flight.record(
                    "scale_up", frm=old, to=n, reason=reason[:200]
                )
                logger.info("fleet scale up %d -> %d (%s)", old, n, reason)
            else:
                retired = [i for i in sorted(self._slots) if i >= n]
                for i in retired:
                    slot = self._slots.pop(i)
                    if slot.proc is not None:
                        try:
                            slot.proc.terminate()
                        except Exception:
                            pass
                        # the tick reaps (SIGKILL past the grace) — see
                        # _reap_retired
                        self._retired.append(
                            (i, slot.proc, time.monotonic() + 5.0)
                        )
                self._c_scale_down.inc()
                self._flight.record(
                    "scale_down", frm=old, to=n, retired=retired,
                    reason=reason[:200],
                )
                logger.info("fleet scale down %d -> %d (%s)", old, n, reason)
            return n

    # -- the supervision loop ----------------------------------------------
    def _tick(self) -> None:
        now = time.monotonic()
        self._kill_wedged(now)
        with self._lock:
            self._reap_retired(now)
            self._reap_deaths(now)
            self._update_circuit(now)
            if not self._circuit_open:
                retired_idxs = {idx for idx, _, _ in self._retired}
                for slot in self._slots.values():
                    if (
                        slot.proc is None
                        and now >= slot.next_spawn_t
                        # a re-grown slot waits for its retiree to be
                        # fully reaped (identity exclusivity, above)
                        and slot.idx not in retired_idxs
                    ):
                        self._spawn(slot)

    def _reap_retired(self, now: float) -> None:
        """Finish off scale-down retirees: join the exited, SIGKILL the
        ones that outlived the terminate grace. A retiree must be fully
        dead before its slot index can be re-grown — its wire identity is
        the slot's, and two live claimants would flip-flop the ROUTER's
        reply routing (handover takes the newest connect)."""
        still = []
        for idx, p, kill_at in self._retired:
            try:
                if not p.is_alive():
                    p.join(timeout=0)
                    continue
                if now >= kill_at:
                    p.kill()
            except Exception:
                pass
            still.append((idx, p, kill_at))
        self._retired = still

    def _reap_deaths(self, now: float) -> None:
        for slot in self._slots.values():
            p = slot.proc
            if p is None or p.is_alive():
                continue
            try:
                p.join(timeout=0)
            except Exception:
                pass
            uptime = now - slot.started_t
            # a slot that ran stably before dying starts a fresh failure
            # streak — backoff punishes crash LOOPS, not one-off kills
            slot.failures = (
                1 if uptime >= self.spec.stable_after_s else slot.failures + 1
            )
            delay = self.spec.backoff_s(slot.failures)
            slot.next_spawn_t = now + delay
            slot.proc = None
            self._c_deaths.inc()
            self._flight.record(
                "server_death",
                slot=slot.idx,
                exitcode=getattr(p, "exitcode", None),
                uptime_s=round(uptime, 3),
                failures=slot.failures,
                respawn_in_s=round(delay, 3),
            )
            logger.warn(
                "env server slot %d died (exit %s, up %.1fs) — respawn in "
                "%.2fs", slot.idx, getattr(p, "exitcode", None), uptime, delay,
            )

    def _kill_wedged(self, now: float) -> None:
        """Act on the MASTER'S liveness verdicts: a prune event for a slot
        whose process is still alive means the server is wedged (silent on
        the wire past ``actor_timeout``) — kill it so the normal respawn
        path takes over. The supervisor never second-guesses the master's
        account with heartbeats of its own."""
        events = self._flight.events_since(self._events_after, kind="prune")
        if not events:
            return
        self._events_after = max(ev[0] for ev in events)
        for t, _, fields in events:
            ident_repr = str(fields.get("ident", ""))
            with self._lock:
                idx = self._slot_for_ident(ident_repr)
                slot = self._slots.get(idx) if idx is not None else None
                proc = slot.proc if slot is not None else None
                # only a prune issued AGAINST the current incarnation is a
                # wedge verdict; one recorded before this process started
                # refers to its predecessor
                stale = slot is None or t <= slot.started_t
            if proc is None or stale or not proc.is_alive():
                continue
            self._c_wedged.inc()
            self._flight.record(
                "wedged_kill", slot=slot.idx, ident=ident_repr[:120]
            )
            logger.warn(
                "master pruned slot %d (%s) but its process is alive — "
                "killing the wedged server", slot.idx, ident_repr,
            )
            try:
                proc.kill()
            except Exception:
                pass

    def _slot_for_ident(self, ident_repr: str) -> Optional[int]:
        for idx in self._slots:
            p = self._ident_prefix(idx)
            i = ident_repr.find(p)
            while i != -1:
                nxt = ident_repr[i + len(p) : i + len(p) + 1]
                if nxt == "" or nxt in _IDENT_DELIMS:
                    return idx
                i = ident_repr.find(p, i + 1)
        return None

    def _update_circuit(self, now: float) -> None:
        if self.spec.restart_budget == 0:
            return  # permanently open: respawns disabled by spec
        window = self.spec.budget_window_s
        while self._respawn_times and now - self._respawn_times[0] > window:
            self._respawn_times.popleft()
        n = len(self._respawn_times)
        if not self._circuit_open and n >= self.spec.restart_budget:
            self._circuit_open = True
            self._c_circuit.inc()
            self._flight.record(
                "circuit_open", respawns_in_window=n, window_s=window
            )
            logger.error(
                "respawn circuit OPEN: %d respawns inside %.0fs (budget "
                "%d) — fleet respawns paused", n, window,
                self.spec.restart_budget,
            )
            # a tripped breaker IS the incident: evidence goes to disk now
            self._flight.dump("respawn circuit open")
        elif self._circuit_open and n <= self.spec.restart_budget // 2:
            self._circuit_open = False
            self._flight.record("circuit_close", respawns_in_window=n)
            logger.info(
                "respawn circuit closed (%d respawns left in window)", n
            )

    def _spawn(self, slot: _Slot) -> None:
        if self.spec.wire == "block-shm":
            # the dead incarnation's ring file (possibly another geometry
            # from an older spec) must be gone before the new server
            # creates — reclaim is safe exactly now, with the slot empty
            from distributed_ba3c_tpu.utils import shm

            n = shm.reclaim_stale(
                shm.ring_name(self.spec.pipe_c2s, self._ident_prefix(slot.idx))
            )
            if n:
                self._c_rings.inc(n)
        p = self._factory(slot.idx)
        if isinstance(p, mp.process.BaseProcess):
            ensure_proc_terminate(p)
            start_proc_mask_signal([p])
        else:
            p.start()
        now = time.monotonic()
        slot.proc = p
        slot.started_t = now
        if slot.ever_started:
            self._c_respawns.inc()
            self._respawn_times.append(now)
            self._flight.record(
                "server_respawn", slot=slot.idx, failures=slot.failures
            )
        else:
            slot.ever_started = True
            self._c_spawns.inc()
            self._flight.record("server_spawn", slot=slot.idx)
