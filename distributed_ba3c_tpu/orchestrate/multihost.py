"""Multi-host worker launch: ``scripts/launch_multihost.sh`` as Python.

The shell launcher carried three pieces of load-bearing logic — rank
derivation (hostname position in the worker list, ``SLURM_PROCID``
override), the exit-75 relaunch loop (parallel/watchdog.py's rank-failure
semantics: a rank that loses lockstep exits 75 and must be relaunched
with resume), and the finalized-checkpoint resume gate shared with
``orchestrate/learner.py`` (resume ONLY from a checkpoint.json whose
``latest`` is non-null; the run's own checkpoints take precedence over a
caller warm start; a fresh first launch never silently resumes). All
three now live here, counted and flight-recorded like every other
orchestration decision; the shell script remains as a thin shim that
warns and delegates (tests/test_launch_script.py pins the contract
against whichever entry the operator uses).

Entry point::

    python -m distributed_ba3c_tpu.orchestrate \\
        --multihost "host1:9900,host2:9900" -- --logdir runs/x [...]
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
from typing import List, Optional

from distributed_ba3c_tpu import telemetry
from distributed_ba3c_tpu.orchestrate.learner import finalized_step
from distributed_ba3c_tpu.utils import logger


def rank_from_hosts(
    worker_hosts: str, hostname: Optional[str] = None
) -> int:
    """This task's rank: ``SLURM_PROCID`` when set, else the position of
    the short hostname in the worker list (the shell launcher's rule)."""
    procid = os.environ.get("SLURM_PROCID")
    if procid:
        return int(procid)
    short = (hostname or socket.gethostname()).split(".")[0]
    hosts = [h.split(":")[0].split(".")[0] for h in worker_hosts.split(",")]
    try:
        return hosts.index(short)
    except ValueError:
        raise SystemExit(
            f"hostname {short!r} not in --multihost list {hosts} and no "
            "SLURM_PROCID set — cannot derive this task's rank"
        )


def _flag_value(args: List[str], name: str) -> Optional[str]:
    """Last value of ``--name X`` / ``--name=X`` in an argv list."""
    val = None
    for i, a in enumerate(args):
        if a == name and i + 1 < len(args):
            val = args[i + 1]
        elif a.startswith(name + "="):
            val = a[len(name) + 1:]
    return val


def _strip_flag(args: List[str], name: str) -> List[str]:
    out: List[str] = []
    skip = False
    for a in args:
        if skip:
            skip = False
            continue
        if a == name:
            skip = True
            continue
        if a.startswith(name + "="):
            continue
        out.append(a)
    return out


class MultihostLauncher:
    """One worker rank's supervised launch loop.

    ``train_args`` go to train.py verbatim plus the worker identity
    (``--job_name worker --worker_hosts ... --task_index <rank>``). Exit
    75 (lost lockstep) relaunches with the resume gate:

    - a FINALIZED run-local checkpoint (``<logdir>/checkpoints`` with
      checkpoint.json ``latest`` non-null) wins — a caller ``--load`` is
      a warm-START source and reusing it would replay every step since
      launch forever (tests/test_launch_script.py);
    - otherwise a caller ``--load`` is kept (warm start still the best
      resume point before the first collective save);
    - otherwise relaunch fresh. The FIRST launch never auto-resumes even
      over a reused logdir (a silent resume could "complete" a finished
      run with zero training).

    Any other exit code propagates.
    """

    def __init__(
        self,
        worker_hosts: str,
        train_args: List[str],
        task_index: Optional[int] = None,
        train_py: str = "train.py",
        python: Optional[str] = None,
    ):
        self.worker_hosts = worker_hosts
        self.train_args = list(train_args)
        self.task_index = (
            rank_from_hosts(worker_hosts) if task_index is None else task_index
        )
        # CWD-relative by default, like the shell launcher — operators run
        # it from the repo root and the launch-script tests stub train.py
        # in their working directory
        self.train_py = train_py
        self.python = python or sys.executable
        self.logdir = _flag_value(self.train_args, "--logdir") or ""
        tele = telemetry.registry("orchestrator")
        self._c_relaunches = tele.counter("multihost_relaunches_total")
        self._flight = telemetry.flight_recorder()

    def _resume_args(self) -> List[str]:
        """The relaunch argv under the resume gate (see class docstring)."""
        args = list(self.train_args)
        run_ckpts = os.path.join(self.logdir, "checkpoints")
        if self.logdir and finalized_step(run_ckpts) is not None:
            if _flag_value(args, "--load") is not None:
                logger.warn(
                    "[multihost] resume: replacing caller --load with the "
                    "run's own %s (progress since launch lives there)",
                    run_ckpts,
                )
                args = _strip_flag(args, "--load")
            return args + ["--load", run_ckpts]
        if _flag_value(args, "--load") is not None:
            logger.warn(
                "[multihost] exit 75, no run-local checkpoint saved yet — "
                "retrying with the caller's --load (warm start)"
            )
            return args
        logger.warn(
            "[multihost] exit 75 but no saved checkpoint to resume from "
            "(logdir=%r) — relaunching fresh", self.logdir,
        )
        return args

    def run(self) -> int:
        logger.info(
            "[multihost] worker_hosts=%s task_index=%d",
            self.worker_hosts, self.task_index,
        )
        relaunch = False
        while True:
            args = self._resume_args() if relaunch else list(self.train_args)
            argv = [
                self.python, self.train_py,
                "--job_name", "worker",
                "--worker_hosts", self.worker_hosts,
                "--task_index", str(self.task_index),
            ] + args
            rc = subprocess.call(argv)
            if rc != 75:
                return rc
            relaunch = True
            self._c_relaunches.inc()
            self._flight.record(
                "multihost_relaunch",
                task_index=self.task_index,
                resume_step=finalized_step(
                    os.path.join(self.logdir, "checkpoints")
                )
                if self.logdir
                else None,
            )
            logger.warn(
                "[multihost] rank lost lockstep (exit 75) — relaunching "
                "with resume"
            )
