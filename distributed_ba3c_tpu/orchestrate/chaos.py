"""Chaos injection: randomly SIGKILL supervised env servers, on purpose.

The acceptance story for the whole orchestration stack
(docs/orchestration.md, scripts/chaos_bench.py): with servers being
SIGKILLed at random mid-run, the plane must hold >=90% of its no-chaos
steady-state throughput — the master prunes/incarnation-resets, the
supervisor respawns with backoff, and the telemetry plane shows every
event. The monkey is deliberately dumb: pick a live slot, SIGKILL it (no
goodbye on the wire — exactly an OOM kill), wait, repeat. Seeded RNG so a
failing chaos run replays its exact kill sequence.
"""

from __future__ import annotations

import random
from typing import Optional

from distributed_ba3c_tpu import telemetry
from distributed_ba3c_tpu.orchestrate.supervisor import FleetSupervisor
from distributed_ba3c_tpu.utils import logger
from distributed_ba3c_tpu.utils.concurrency import StoppableThread


class ChaosMonkey(StoppableThread):
    """SIGKILL a random live server every ``interval_s`` (+- ``jitter_s``),
    up to ``max_kills`` (None = until stopped)."""

    def __init__(
        self,
        supervisor: FleetSupervisor,
        interval_s: float = 3.0,
        jitter_s: float = 0.5,
        max_kills: Optional[int] = None,
        seed: int = 0,
        initial_delay_s: Optional[float] = None,
    ):
        super().__init__(daemon=True, name="ChaosMonkey")
        self.supervisor = supervisor
        self.interval_s = interval_s
        self.jitter_s = jitter_s
        self.max_kills = max_kills
        self.kills = 0
        self._rng = random.Random(seed)
        self._initial_delay_s = (
            interval_s if initial_delay_s is None else initial_delay_s
        )
        self._flight = telemetry.flight_recorder()
        self._c_kills = telemetry.registry("orchestrator").counter(
            "chaos_kills_total"
        )

    def run(self) -> None:
        self._stop_evt.wait(self._initial_delay_s)
        while not self.stopped():
            if self.max_kills is not None and self.kills >= self.max_kills:
                return
            self.kill_one()
            self._stop_evt.wait(
                max(0.05, self.interval_s + self._rng.uniform(
                    -self.jitter_s, self.jitter_s
                ))
            )

    def kill_one(self) -> Optional[int]:
        """SIGKILL one random live slot; returns its index (None if the
        fleet had no live victim this instant)."""
        live = self.supervisor.live_slots()
        if not live:
            return None
        idx, proc = self._rng.choice(live)
        if not self.supervisor.sigkill_slot(idx):
            return None
        self.kills += 1
        self._c_kills.inc()
        self._flight.record(
            "chaos_kill", slot=idx, pid=getattr(proc, "pid", None),
            kill_no=self.kills,
        )
        logger.warn(
            "chaos: SIGKILLed env server slot %d (kill %d%s)", idx,
            self.kills,
            f"/{self.max_kills}" if self.max_kills is not None else "",
        )
        return idx
