"""Elastic self-healing fleet orchestration (docs/orchestration.md).

The subsystem that ACTS on what the actor plane survives and the
telemetry plane measures — ROADMAP open item 5:

- :class:`FleetSpec` (orchestrate/spec.py) — the declarative fleet
  description: env-server shape + sizing bounds + respawn policy.
- :class:`FleetSupervisor` (orchestrate/supervisor.py) — spawns the
  fleet, watches the master's telemetry account for deaths/wedges,
  respawns with exponential backoff behind a restart-budget circuit
  breaker, scales between ``fleet_min``/``fleet_max``.
- :class:`Autoscaler` / :class:`AutoscalerPolicy`
  (orchestrate/autoscaler.py) — the policy loop turning FastQueue
  depth/blocked-put backpressure into scale decisions.
- :class:`LearnerSupervisor` (orchestrate/learner.py) — checkpoint
  failover: a killed learner resumes from the last finalized checkpoint
  without operator action (``python -m distributed_ba3c_tpu.orchestrate``).
- :class:`ChaosMonkey` (orchestrate/chaos.py) — the acceptance harness's
  fault injector (scripts/chaos_bench.py gates on >=90% of no-chaos
  throughput under random SIGKILLs).
- :class:`PodSupervisor` / :class:`PodLearnerPlane` (orchestrate/pod.py)
  — pod mode: N supervised actor-host processes against one
  bounded-staleness learner (docs/pod.md; ``--pod_hosts``).
- :class:`MultihostLauncher` (orchestrate/multihost.py) — the retired
  scripts/launch_multihost.sh loop: rank derivation + exit-75 relaunch
  under the finalized-checkpoint resume gate (``--multihost``).
- :class:`TopologySpec` (orchestrate/topology.py) — ONE declarative
  document for the whole topology: fleets, pod hosts, learner, serving
  replicas, SLO/staleness bounds, chaos/netchaos schedules
  (docs/topology.md; ``--topology spec.json`` / ``--dump_topology``).
- :class:`Reconciler` (orchestrate/reconcile.py) — the single generic
  observe→diff→act loop driving every resource above through one
  :class:`Reconcilable` protocol, with per-resource backoff, a
  topology-wide restart-budget circuit breaker, and flight-recorded
  decisions (``tele/reconciler/*``).

Every decision is exported as ``tele/orchestrator/*`` series and
flight-recorder events — scale/respawn/failover actions are always
postmortem-visible.
"""

from __future__ import annotations

from distributed_ba3c_tpu.orchestrate.autoscaler import (  # noqa: F401
    Autoscaler,
    AutoscalerPolicy,
    http_signals,
    master_signals,
)
from distributed_ba3c_tpu.orchestrate.chaos import ChaosMonkey  # noqa: F401
from distributed_ba3c_tpu.orchestrate.learner import (  # noqa: F401
    LearnerSupervisor,
    finalized_step,
)
from distributed_ba3c_tpu.orchestrate.multihost import (  # noqa: F401
    MultihostLauncher,
)
from distributed_ba3c_tpu.orchestrate.pod import (  # noqa: F401
    PodLearnerPlane,
    PodSupervisor,
    host_argv,
)
from distributed_ba3c_tpu.orchestrate.reconcile import (  # noqa: F401
    Action,
    FleetResource,
    LearnerResource,
    PolicyResource,
    Reconcilable,
    Reconciler,
    ServingResource,
)
from distributed_ba3c_tpu.orchestrate.spec import FleetSpec  # noqa: F401
from distributed_ba3c_tpu.orchestrate.supervisor import (  # noqa: F401
    FleetSupervisor,
    default_factory,
)
from distributed_ba3c_tpu.orchestrate.topology import (  # noqa: F401
    TopologyError,
    TopologySpec,
)
