"""Experiment-tracking channels (Neptune-equivalent surface).

Reference equivalent: the deepsense Neptune integration (SURVEY.md §2.7 #24)
— live channels (score, cost, fps) streamed from the run. Rebuild: a
dependency-free JSONL channel writer with the same shape (named channels of
(x, y) points), pluggable into the callback list; any dashboard can tail the
file. TensorBoard: point `jax.profiler`/TensorBoard at the logdir for device
traces (utils/profiling.py); scalar history lives in stat.json + channels.
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

from distributed_ba3c_tpu.train.callbacks import Callback


class ChannelWriter:
    """Append-only JSONL: one line per point {channel, x, y, ts}."""

    def __init__(self, path: str):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = open(path, "a", buffering=1)

    def send(self, channel: str, x: float, y: float) -> None:
        self._f.write(
            json.dumps(
                {"channel": channel, "x": x, "y": y, "ts": time.time()}
            )
            + "\n"
        )

    def close(self) -> None:
        self._f.close()


class ExperimentLogger(Callback):
    """Streams the stat_holder's per-epoch record to channels.jsonl."""

    def __init__(self, channels=("mean_score", "max_score", "fps", "loss")):
        self.channels = channels
        self._writer: Optional[ChannelWriter] = None

    def before_train(self):
        log_dir = self.trainer.config.log_dir
        if log_dir:
            self._writer = ChannelWriter(os.path.join(log_dir, "channels.jsonl"))

    def trigger_epoch(self):
        if self._writer is None:
            return
        # read the just-finalized record (StatPrinter runs before this
        # callback in the standard ordering; see cli.py callback order note)
        if self.trainer.stat_holder.stat_history:
            rec = self.trainer.stat_holder.stat_history[-1]
            x = rec.get("global_step", self.trainer.global_step)
            for ch in self.channels:
                if ch in rec:
                    self._writer.send(ch, x, rec[ch])

    def after_train(self):
        if self._writer is not None:
            self._writer.close()
