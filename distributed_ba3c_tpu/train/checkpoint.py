"""Checkpoint/resume via orbax, with keep-best semantics.

Reference equivalent (SURVEY.md §5 checkpoint/resume): ``ModelSaver`` →
``tf.train.Saver`` periodic writes, ``MaxSaver`` keep-best-score copy,
``--load`` → ``SaverRestore``. Here: orbax saves of the full TrainState
(params + opt state + step), a ``latest`` pointer, and a ``best`` pointer
updated when the monitored stat improves.
"""

from __future__ import annotations

import json
import os
from typing import Any, Optional

import jax
import orbax.checkpoint as ocp


class CheckpointManager:
    """Saves/restores TrainState pytrees under ``root/ckpt-<step>``."""

    def __init__(self, root: str, max_to_keep: int = 3):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        self._ckpt = ocp.StandardCheckpointer()
        self.max_to_keep = max_to_keep
        self._meta_path = os.path.join(self.root, "checkpoint.json")
        self._meta = {"all": [], "latest": None, "best": None, "best_score": None}
        if os.path.isfile(self._meta_path):
            with open(self._meta_path) as f:
                self._meta = json.load(f)
        self._run_meta_path = os.path.join(self.root, "run_meta.json")

    def write_run_meta(self, **fields):
        """Persist run-shape facts (steps_per_epoch, batch shape, ...) next to
        the checkpoints so a resume can detect a mismatched schedule: the
        epoch counter derives from step // steps_per_epoch, so resuming with
        a different shape silently stretches the LR/beta anneal."""
        if jax.process_index() != 0:
            return
        tmp = self._run_meta_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(fields, f)
        os.replace(tmp, self._run_meta_path)

    def read_run_meta(self) -> dict:
        if os.path.isfile(self._run_meta_path):
            with open(self._run_meta_path) as f:
                return json.load(f)
        return {}

    def _write_meta(self):
        tmp = self._meta_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self._meta, f)
        os.replace(tmp, self._meta_path)

    def _dir(self, step: int) -> str:
        return os.path.join(self.root, f"ckpt-{step}")

    def save(self, state: Any, step: int) -> str:
        """Save. In multi-process runs EVERY process must call this with the
        same path: orbax synchronizes all processes on save (a chief-only
        call deadlocks the chief in the barrier — seen in the 2-process CLI
        test). Metadata and pruning stay chief-only below."""
        path = self._dir(step)
        self._ckpt.save(path, jax.device_get(state), force=True)
        # StandardCheckpointer is async in this orbax version; commit before
        # pruning/meta so `latest` never points at an in-flight write.
        wait = getattr(self._ckpt, "wait_until_finished", None)
        if callable(wait):
            wait()
        if jax.process_index() != 0:
            return path
        if step not in self._meta["all"]:
            # re-saving an existing step (a killed run re-driven over the
            # same logdir) must not duplicate the bookkeeping entry
            self._meta["all"].append(step)
        self._meta["latest"] = step
        # prune oldest beyond max_to_keep; NEVER delete the best or the
        # just-saved latest (with max_to_keep=1 the old loop could delete the
        # checkpoint it had just written while `latest` still pointed at it)
        protected = {self._meta.get("best"), step}
        keep = list(self._meta["all"])
        deletable = [s for s in keep if s not in protected]
        while len(keep) > self.max_to_keep and deletable:
            victim = deletable.pop(0)
            keep.remove(victim)
            vdir = self._dir(victim)
            if os.path.isdir(vdir):
                import shutil

                shutil.rmtree(vdir)
        self._meta["all"] = keep
        self._write_meta()
        return path

    def mark_best(self, step: int, score: float) -> bool:
        """Record ``step`` as best if ``score`` improves; returns True if so."""
        best = self._meta.get("best_score")
        if best is None or score > best:
            self._meta["best"] = step
            self._meta["best_score"] = float(score)
            if jax.process_index() == 0:
                self._write_meta()
            return True
        return False

    @property
    def all_steps(self) -> list:
        """Every kept step, ascending, deduplicated (the eval-sweep
        enumeration surface; metadata written before the dedup-on-save fix
        may carry repeats)."""
        return sorted(set(self._meta.get("all", [])))

    @property
    def latest_step(self) -> Optional[int]:
        return self._meta.get("latest")

    @property
    def best_step(self) -> Optional[int]:
        return self._meta.get("best")

    def restore(self, target: Any, step: Optional[int] = None) -> Any:
        """Restore into the structure of ``target`` (an abstract or concrete
        TrainState). Defaults to the latest step."""
        if step is None:
            step = self.latest_step
        assert step is not None, "no checkpoint to restore"
        return self._ckpt.restore(self._dir(step), target)
