"""The trainer: epochs × steps main loop over the mesh-sharded update.

Reference equivalent (SURVEY.md §2.5 #13-15, call stack §3.1):
``Trainer.train() -> main_loop()`` with callback dispatch. What changed,
TPU-first:

- ``run_step``'s ``sess.run(train_op)`` + async PS gradient push becomes one
  jitted shard_map step with the grads psum'd over the mesh (§3.4 replaced).
- ``QueueInput``/``EnqueueThread`` become ``TrainFeed`` (host batcher thread)
  + ``jax.device_put`` at the head of each step: device dispatch is async,
  so staging the next batch overlaps the previous step's execution (see the
  ``run_step`` note — no explicit double buffer exists or is needed).
- The predict towers' shared-variable reads become an explicit params publish
  to the BatchedPredictor every ``publish_every`` steps (on-device ref swap,
  no host copy).
"""

from __future__ import annotations

import dataclasses
import queue
import time
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from distributed_ba3c_tpu import telemetry
from distributed_ba3c_tpu.config import BA3CConfig
from distributed_ba3c_tpu.train.callbacks import Callback, Callbacks
from distributed_ba3c_tpu.utils import logger
from distributed_ba3c_tpu.utils.stats import StatCounter, StatHolder


@dataclasses.dataclass
class TrainLoopConfig:
    """Loop shape + wiring (reference ``TrainConfig``, SURVEY.md §2.5 #13)."""

    steps_per_epoch: int = 1000
    max_epoch: int = 100
    log_dir: Optional[str] = None
    publish_every: int = 1  # params → predictor every N steps
    feed_timeout: float = 120.0
    # multi-host only: secs without epoch progress before declaring a peer
    # rank dead and exiting 75 (0 → 600s default when process_count > 1)
    rank_stall_timeout: float = 0.0


class Trainer:
    """Owns the TrainState, the jitted step, and the callback lifecycle."""

    def __init__(
        self,
        config: TrainLoopConfig,
        cfg: BA3CConfig,
        step_fn: Callable,  # from make_train_step
        state,  # TrainState (host or device)
        feed,  # TrainFeed-like: next_batch(timeout)
        callbacks: List[Callback],
        predictor=None,  # BatchedPredictor to publish params to
        score_queue: Optional[queue.Queue] = None,
        is_chief: bool = True,
        samples_per_step: Optional[int] = None,
    ):
        self.config = config
        self.cfg = cfg
        self.step_fn = step_fn
        self.state = jax.device_put(state, step_fn.state_sharding)
        self.feed = feed
        self.predictor = predictor
        self.score_queue = score_queue
        self.is_chief = is_chief

        self.hyperparams: Dict[str, float] = {
            "learning_rate": cfg.learning_rate,
            "entropy_beta": cfg.entropy_beta,
        }
        self.global_step = 0
        self.epoch_num = 0
        self.batch_size = samples_per_step or cfg.batch_size
        self.stat_holder = StatHolder(config.log_dir)
        self.score_counter: Optional[StatCounter] = StatCounter()
        self.last_mean_score: Optional[float] = None
        self.ckpt_manager = None  # set by ModelSaver
        self.metrics = None
        self._pending_trace = None  # sampled trace between stage + step
        self._callbacks = Callbacks(callbacks)

        # telemetry (docs/observability.md): the learner registry is the
        # single account of training progress — StatPrinter derives its fps
        # from these counters instead of keeping its own step count
        tele = telemetry.registry("learner")
        self._c_steps = tele.counter("train_steps_total")
        self._c_samples = tele.counter("train_samples_total")
        self._h_step = tele.histogram("step_s", unit=1e-6)

    # -- predictor glue ----------------------------------------------------
    def predictor_fn(self) -> Callable[[np.ndarray], np.ndarray]:
        """Greedy batched predict on CURRENT params (for Evaluator)."""
        assert self.predictor is not None

        def predict(states: np.ndarray) -> np.ndarray:
            _, _, greedy_actions = self.predictor.predict_batch(states)
            return greedy_actions

        return predict

    def _publish_params(self):
        if self.predictor is not None:
            # COPY before publishing: the train step donates the state buffers
            # (donate_argnums), so the predictor must never alias them — an
            # in-flight forward reading a donated-and-reused buffer crashes in
            # native code. The copy is one small device-to-device transfer.
            params = jax.tree_util.tree_map(jnp.copy, self.state.params)
            # sanctioned single-host publish: the version IS the train
            # step (publish_every cadence), and the pod plane replaces
            # this path entirely when hosts serve from the stale cache
            self.predictor.update_params(params)  # ba3clint: disable=A10

    def _drain_scores(self):
        if self.score_queue is None:
            return
        while True:
            try:
                self.score_counter.feed(self.score_queue.get_nowait())
            except queue.Empty:
                return

    # -- loop --------------------------------------------------------------
    def _put(self, v, sharding):
        """Host batch → sharded device array.

        Single-host: plain device_put. Multi-host: each process feeds its
        LOCAL rows and jax assembles the global array from per-host shards
        (the replacement for the reference's per-worker queue; each TF
        worker likewise only saw its own simulators' batches, SURVEY §3.4).
        """
        if jax.process_count() > 1:
            return jax.make_array_from_process_local_data(sharding, v)
        return jax.device_put(v, sharding)

    def _next_device_batch(self):
        if getattr(self.feed, "is_device_ingest", False):
            # staged pipeline (data/staging.py DeviceIngest): the batch's
            # H2D was dispatched behind the PREVIOUS step (run_step's
            # prefetch call) whenever the feed kept up — the claim here is
            # then just a handoff, and the ingest/h2d_copy hops were
            # already recorded by the pipeline
            batch = self.feed.next_batch(timeout=self.config.feed_timeout)
            self._pending_trace = batch.pop("_trace", None)
            return batch
        batch = self.feed.next_batch(timeout=self.config.feed_timeout)
        # a sampled trace rode the batch through the feed (tracing.py):
        # claim it before staging — device_put must never see the ref
        trace = batch.pop("_trace", None)
        sharding = self.step_fn.batch_sharding
        if isinstance(sharding, dict):
            out = {k: self._put(v, sharding[k]) for k, v in batch.items()}
        else:
            out = {k: self._put(v, sharding) for k, v in batch.items()}
        if trace is not None:
            # feed handoff -> staged on device (host-side ingest hop)
            self._pending_trace = trace.hop("ingest", "learner")
        return out

    def run_step(self) -> None:
        # Overlap note: step_fn dispatch is ASYNC, so fetching/staging the
        # next batch at the head of the next call already overlaps the
        # device's execution of this step — no explicit double buffer is
        # needed (and none is claimed; a post-step staging fetch was tried
        # and reverted: it could starve at shutdown and discard the final
        # step's accounting). The overlap is bounded by trigger_step
        # callbacks that fetch metrics (StatPrinter samples every N steps).
        t0 = time.monotonic()
        batch = self._next_device_batch()
        if self._pending_trace is not None:
            # sampled steps only: the jax.profiler step region carries the
            # trace/span ids, so a chip-session capture lines up with the
            # host spans by id (utils/profiling.py; no-op cost when no
            # profiler session is attached)
            from distributed_ba3c_tpu.utils.profiling import step_annotation

            with step_annotation(
                "train_step", self.global_step,
                trace_id=self._pending_trace.trace_id,
                span_id=self._pending_trace.parent_id,
            ):
                self.state, self.metrics = self.step_fn(
                    self.state,
                    batch,
                    self.hyperparams["entropy_beta"],
                    self.hyperparams["learning_rate"],
                )
        else:
            self.state, self.metrics = self.step_fn(
                self.state,
                batch,
                self.hyperparams["entropy_beta"],
                self.hyperparams["learning_rate"],
            )
        self.global_step += 1
        prefetch = getattr(self.feed, "prefetch", None)
        if prefetch is not None:
            # staged pipeline: dispatch the NEXT batch's H2D right behind
            # the step dispatch above, so the transfer overlaps the
            # device's execution of THIS step. Non-blocking by contract —
            # the shutdown-starvation and lost-accounting failure modes
            # that reverted the old post-step staging fetch (see the
            # Overlap note above) were properties of a BLOCKING fetch
            prefetch()
        if self._pending_trace is not None:
            # host-side dispatch of the update (device execution is async;
            # a chip-session jax.profiler capture correlates via the
            # step_annotation trace/span tags — utils/profiling.py)
            self._pending_trace.hop(
                "learner_step", "learner", tags={"step": self.global_step}
            )
            self._pending_trace = None
        # step latency here covers feed wait + staging + async dispatch —
        # the host-side budget (device execution overlaps the next call)
        self._h_step.observe(time.monotonic() - t0)
        self._c_steps.inc()
        self._c_samples.inc(self.batch_size)
        if self.global_step % self.config.publish_every == 0:
            self._publish_params()
        self._drain_scores()
        self._callbacks.trigger_step(self.metrics)

    def train(self) -> None:
        self._callbacks.setup(self)
        if self.config.log_dir:
            logger.set_logger_dir(self.config.log_dir)
        self._callbacks.before_train()
        self._publish_params()
        # multi-host rank-failure detection (SURVEY §5): a dead peer wedges
        # this rank in the next psum forever; the watchdog converts that into
        # a bounded-time exit 75 so the launcher can resume from checkpoints
        from distributed_ba3c_tpu.parallel.watchdog import (
            LockstepWatchdog,
            resolve_timeout,
        )

        try:
            with LockstepWatchdog(
                resolve_timeout(getattr(self.config, "rank_stall_timeout", 0)),
                what=f"rank {jax.process_index()}/{jax.process_count()} "
                "epoch loop",
            ) as watchdog:
                for self.epoch_num in range(1, self.config.max_epoch + 1):
                    for _ in range(self.config.steps_per_epoch):
                        self.run_step()
                    self._callbacks.trigger_epoch()
                    watchdog.beat()
        except KeyboardInterrupt:
            logger.warn("training interrupted")
        except queue.Empty:
            # feed starvation is a FAILURE (dead actor plane), not a clean
            # shutdown — propagate so launchers/CI see a non-zero exit
            logger.error(
                "train feed starved for %.0fs — actor plane dead?",
                self.config.feed_timeout,
            )
            raise RuntimeError("train feed starved; actor plane dead") from None
        finally:
            self._callbacks.after_train()
            # close the TB event writer (a never-joined background thread
            # otherwise — the exact leak class behind the round-1 deadlock)
            self.stat_holder.close()

    # -- resume ------------------------------------------------------------
    def restore(self, ckpt_dir: str, step: Optional[int] = None) -> None:
        """Resume params/opt/step from a checkpoint directory (--load)."""
        from distributed_ba3c_tpu.train.checkpoint import CheckpointManager

        mgr = CheckpointManager(ckpt_dir)
        restored = mgr.restore(jax.device_get(self.state), step)
        self.state = jax.device_put(restored, self.step_fn.state_sharding)
        self.global_step = int(self.state.step)
        self._publish_params()
        logger.info("restored checkpoint at step %d", self.global_step)
