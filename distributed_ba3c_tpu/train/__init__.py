"""Trainer, callbacks, checkpointing, eval.

Reference equivalent: ``tensorpack/train/`` + ``tensorpack/callbacks/`` +
``src/common.py`` (SURVEY.md §2.5, §2.7, §2.1 #4). The epochs×steps main loop
and callback lifecycle survive; the gradient plane inside ``run_step`` is the
mesh-sharded jitted update from :mod:`distributed_ba3c_tpu.parallel`.
"""

from distributed_ba3c_tpu.train.callbacks import (
    Callback,
    Callbacks,
    Evaluator,
    HumanHyperParamSetter,
    HyperParamSetterWithFunc,
    MaxSaver,
    ModelSaver,
    PeriodicTrigger,
    ScheduledHyperParamSetter,
    StartProcOrThread,
    StatPrinter,
)
from distributed_ba3c_tpu.train.checkpoint import CheckpointManager
from distributed_ba3c_tpu.train.trainer import Trainer, TrainLoopConfig

__all__ = [
    "Callback",
    "Callbacks",
    "Evaluator",
    "HumanHyperParamSetter",
    "HyperParamSetterWithFunc",
    "MaxSaver",
    "ModelSaver",
    "PeriodicTrigger",
    "ScheduledHyperParamSetter",
    "StartProcOrThread",
    "StatPrinter",
    "CheckpointManager",
    "Trainer",
    "TrainLoopConfig",
]
