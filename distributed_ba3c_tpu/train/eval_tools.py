"""Shared checkpoint-evaluation setup for the standalone eval scripts.

One construction path for (CheckpointManager, restore target, on-device
greedy evaluator) so `scripts/eval_fused.py` and `scripts/eval_sweep.py`
cannot drift — the n_eval rounding here is load-bearing: the evaluator
shards its env batch over the mesh's data axis, so the env count must be a
positive multiple of it or envs are silently dropped (and a threshold gate
like ``n >= nr_eval`` becomes unsatisfiable).
"""

from __future__ import annotations

import jax

from distributed_ba3c_tpu.config import BA3CConfig
from distributed_ba3c_tpu.envs import jaxenv
from distributed_ba3c_tpu.fused.loop import make_greedy_eval
from distributed_ba3c_tpu.models.a3c import BA3CNet
from distributed_ba3c_tpu.ops.gradproc import make_optimizer
from distributed_ba3c_tpu.parallel.mesh import DATA_AXIS, make_mesh
from distributed_ba3c_tpu.parallel.train_step import create_train_state
from distributed_ba3c_tpu.train.checkpoint import CheckpointManager


def make_checkpoint_evaluator(
    env_spec: str, load: str, nr_eval: int, max_steps: int, fc_units: int = 512
):
    """Returns ``(mgr, target, evaluate, n_eval)``.

    ``target`` is a host-side TrainState structure for ``mgr.restore``;
    ``evaluate(params, seed_int)`` runs the on-device greedy Evaluator over
    ``n_eval`` envs (``nr_eval`` rounded up to a positive multiple of the
    mesh's data-axis size).
    """
    env = jaxenv.get_env(env_spec.split(":", 1)[1])
    cfg = BA3CConfig(num_actions=env.num_actions, fc_units=fc_units)
    model = BA3CNet(num_actions=cfg.num_actions, fc_units=cfg.fc_units)
    opt = make_optimizer(cfg.learning_rate, cfg.adam_epsilon, cfg.grad_clip_norm)
    target = jax.device_get(
        create_train_state(jax.random.PRNGKey(0), model, cfg, opt)
    )
    mgr = CheckpointManager(load)
    mesh = make_mesh()
    n_data = mesh.shape[DATA_AXIS]
    n_eval = max(n_data, (max(nr_eval, 1) + n_data - 1) // n_data * n_data)
    evaluate = make_greedy_eval(
        model, cfg, mesh, env, n_envs=n_eval, max_steps=max_steps
    )
    return mgr, target, evaluate, n_eval
