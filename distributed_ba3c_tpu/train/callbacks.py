"""Callback system: lifecycle hooks around the training loop.

Reference equivalents (SURVEY.md §2.7): ``Callback``/``Callbacks``/
``PeriodicTrigger`` (``callbacks/{base,group}.py`` #19), ``ModelSaver``/
``MaxSaver`` (``callbacks/common.py`` #20), ``ScheduledHyperParamSetter``/
``HyperParamSetterWithFunc``/``HumanHyperParamSetter`` (``callbacks/param.py``
#21), ``StatPrinter`` (``callbacks/stats.py`` #22), ``StartProcOrThread``
(``callbacks/concurrency.py`` #23). Hook order in the loop matches §3.1:
``before_train`` → per-step ``trigger_step`` → per-epoch ``trigger_epoch`` →
``after_train``.
"""

from __future__ import annotations

import os
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from distributed_ba3c_tpu.utils import logger


class Callback:
    trainer = None  # set by setup()

    def setup(self, trainer) -> None:
        self.trainer = trainer

    def before_train(self) -> None:
        pass

    def trigger_step(self, metrics: Optional[dict]) -> None:
        pass

    def trigger_epoch(self) -> None:
        pass

    def after_train(self) -> None:
        pass


class Callbacks(Callback):
    """Dispatch group; after_train runs for every member even on errors."""

    def __init__(self, cbs: Sequence[Callback]):
        self.cbs = list(cbs)

    def setup(self, trainer) -> None:
        for cb in self.cbs:
            cb.setup(trainer)

    def before_train(self) -> None:
        for cb in self.cbs:
            cb.before_train()

    def trigger_step(self, metrics) -> None:
        for cb in self.cbs:
            cb.trigger_step(metrics)

    def trigger_epoch(self) -> None:
        for cb in self.cbs:
            cb.trigger_epoch()

    def after_train(self) -> None:
        for cb in self.cbs:
            try:
                cb.after_train()
            except Exception:  # noqa: BLE001 - teardown must not cascade
                import traceback

                logger.error(
                    "error in %s.after_train:\n%s",
                    type(cb).__name__,
                    traceback.format_exc(),
                )


class PeriodicTrigger(Callback):
    """Run the wrapped callback's trigger_epoch every N epochs (or steps)."""

    def __init__(
        self,
        cb: Callback,
        every_k_epochs: Optional[int] = None,
        every_k_steps: Optional[int] = None,
    ):
        assert (every_k_epochs is None) != (every_k_steps is None)
        self.cb = cb
        self.every_k_epochs = every_k_epochs
        self.every_k_steps = every_k_steps

    def setup(self, trainer):
        super().setup(trainer)
        self.cb.setup(trainer)

    def before_train(self):
        self.cb.before_train()

    def trigger_step(self, metrics):
        if (
            self.every_k_steps
            and self.trainer.global_step % self.every_k_steps == 0
        ):
            self.cb.trigger_epoch()

    def trigger_epoch(self):
        if (
            self.every_k_epochs
            and self.trainer.epoch_num % self.every_k_epochs == 0
        ):
            self.cb.trigger_epoch()

    def after_train(self):
        self.cb.after_train()


class StartProcOrThread(Callback):
    """Start simulator processes / master / predictor threads with the trainer.

    Anything with ``.start()`` works; multiprocessing children are started
    with SIGINT masked and registered for termination at exit.
    """

    def __init__(self, startables: Sequence) -> None:
        self.startables = list(startables)

    def before_train(self) -> None:
        import multiprocessing as mp

        from distributed_ba3c_tpu.utils.concurrency import (
            ensure_proc_terminate,
            start_proc_mask_signal,
        )

        procs = [s for s in self.startables if isinstance(s, mp.process.BaseProcess)]
        others = [s for s in self.startables if not isinstance(s, mp.process.BaseProcess)]
        if procs:
            ensure_proc_terminate(procs)
            start_proc_mask_signal(procs)
        for s in others:
            s.start()
        logger.info(
            "StartProcOrThread: started %d processes, %d threads/servers",
            len(procs),
            len(others),
        )

    def after_train(self) -> None:
        """Full teardown, not just a stop signal: join every thread and
        reap every process so nothing outlives the trainer. Leaked ZMQ /
        predictor threads wedge later in-process jit dispatch (the round-1
        pytest deadlock), so stop → join → close → reap, in that order.
        """
        import multiprocessing as mp

        procs = [s for s in self.startables if isinstance(s, mp.process.BaseProcess)]
        others = [s for s in self.startables if not isinstance(s, mp.process.BaseProcess)]
        # 1. signal everything to stop (cheap, non-blocking)
        for s in others:
            stop = getattr(s, "stop", None)
            if callable(stop):
                stop()
        for p in procs:
            if p.is_alive():
                p.terminate()
        # 2. join threads/servers, then close (tears down ZMQ contexts etc.)
        for s in others:
            join = getattr(s, "join", None)
            if callable(join):
                try:
                    join(timeout=5)
                except TypeError:
                    join()
            close = getattr(s, "close", None)
            if callable(close):
                close()
        # 3. reap children
        for p in procs:
            p.join(timeout=5)
            if p.is_alive():
                p.kill()
                p.join(timeout=5)


class HyperParamSetter(Callback):
    """Base: sets ``trainer.hyperparams[name]`` at epoch boundaries."""

    def __init__(self, name: str):
        self.name = name

    def _value_to_set(self) -> Optional[float]:
        raise NotImplementedError

    def _set(self):
        v = self._value_to_set()
        if v is not None and v != self.trainer.hyperparams.get(self.name):
            logger.info("hyperparam %s <- %.6g", self.name, v)
            self.trainer.hyperparams[self.name] = v

    def before_train(self):
        self._set()

    def trigger_epoch(self):
        self._set()


def anneal_interp(v0: float, v1: float, frac: float, mode: str) -> float:
    """Interpolate a hyperparam between ``v0`` and ``v1`` at ``frac`` ∈ [0,1].

    The ONE schedule formula shared by ScheduledHyperParamSetter and the
    fused loop's ``sched`` (so the two trainers cannot silently diverge).
    ``mode="exp"`` is geometric and requires positive endpoints — a zero or
    negative value would silently cliff / go complex, so it raises instead.
    """
    frac = min(max(frac, 0.0), 1.0)
    if mode == "exp":
        if v0 <= 0 or v1 <= 0:
            raise ValueError(
                f"exp anneal needs positive endpoints, got {v0} -> {v1}"
            )
        return v0 * (v1 / v0) ** frac
    return v0 + frac * (v1 - v0)


class ScheduledHyperParamSetter(HyperParamSetter):
    """Piecewise schedule [(epoch, value), ...]; optional linear/exp interp.

    ``interp="exp"`` interpolates geometrically between knots (both values
    must be positive) — the shape that reaches a low-lr/low-β endgame
    quickly instead of spending half the run at plateau values.
    """

    def __init__(
        self,
        name: str,
        schedule: Sequence[Tuple[int, float]],
        interp: Optional[str] = None,
    ):
        super().__init__(name)
        self.schedule = sorted(schedule)
        assert interp in (None, "linear", "exp")
        self.interp = interp

    def _value_to_set(self) -> Optional[float]:
        e = self.trainer.epoch_num
        laste, lastv = None, None
        for se, sv in self.schedule:
            if se == e:
                return sv
            if se > e:
                if self.interp is None or laste is None:
                    return lastv
                frac = (e - laste) / (se - laste)
                return anneal_interp(lastv, sv, frac, self.interp)
            laste, lastv = se, sv
        return lastv


class HyperParamSetterWithFunc(HyperParamSetter):
    """``func(epoch, current_value) -> value``."""

    def __init__(self, name: str, func: Callable[[int, Optional[float]], float]):
        super().__init__(name)
        self.func = func

    def _value_to_set(self):
        return self.func(
            self.trainer.epoch_num, self.trainer.hyperparams.get(self.name)
        )


def read_hyper_file(path: str) -> Dict[str, float]:
    """Parse a hyper.txt of ``name: value`` lines ({} if absent/unparsable).

    Shared by HumanHyperParamSetter and the fused loop's live-override read
    so every trainer accepts the same file format.
    """
    if not os.path.isfile(path):
        return {}
    try:
        with open(path) as f:
            lines = f.readlines()
    except OSError:
        logger.warn("could not read %s", path)
        return {}
    # parse per line: one typo mid-live-edit must not discard every other
    # override (silently reverting lr/beta to scheduled values)
    out: Dict[str, float] = {}
    for line in lines:
        if ":" not in line:
            continue
        k, _, v = line.partition(":")
        try:
            out[k.strip()] = float(v)
        except ValueError:
            logger.warn("ignoring malformed line in %s: %r", path, line.strip())
    return out


class HumanHyperParamSetter(HyperParamSetter):
    """Read ``<logdir>/<fname>`` lines of ``name: value`` each epoch.

    The reference's human-editable live hyperparam file (SURVEY.md §2.7 #21).
    In multi-host runs only the CHIEF's read counts and the value is
    broadcast — per-host reads racing a mid-run edit (or a lagging shared
    FS) would hand hosts different values and silently diverge the psum'd
    update. Safe collective-wise: every host builds the same setter list,
    so the broadcasts align across ranks.
    """

    def __init__(
        self,
        name: str,
        fname: str = "hyper.txt",
        shared_dir: Optional[str] = None,
    ):
        """``shared_dir``: where to look for the file — in multi-host runs
        pass the CHIEF's logdir (all hosts must agree on ONE file)."""
        super().__init__(name)
        self.fname = fname
        self.shared_dir = shared_dir

    def _value_to_set(self) -> Optional[float]:
        import jax

        if jax.process_count() > 1:
            import numpy as _np
            from jax.experimental import multihost_utils

            v = float("nan")
            if jax.process_index() == 0:
                v0 = self._read_local()
                v = float("nan") if v0 is None else v0
            v = float(
                multihost_utils.broadcast_one_to_all(
                    _np.asarray(v, _np.float64)
                )
            )
            return None if v != v else v
        return self._read_local()

    def _read_local(self) -> Optional[float]:
        log_dir = self.shared_dir or self.trainer.config.log_dir
        if log_dir is None:
            return None
        return read_hyper_file(os.path.join(log_dir, self.fname)).get(self.name)


class StatPrinter(Callback):
    """Samples step metrics, accumulates epoch stats, prints + stat.json.

    Metric names follow the reference's summary plane (SURVEY.md §5):
    loss/policy_loss/value_loss/entropy/grad_norm, mean_score/max_score, fps.
    Device scalars are only fetched every ``sample_every`` steps so the hot
    loop stays async.

    Throughput accounting reads the LEARNER REGISTRY (Trainer.run_step's
    ``train_samples_total`` counter — docs/observability.md): one account
    of progress, shared with the scrape endpoint, instead of a parallel
    step count kept here. The epoch record also absorbs the telemetry
    scalars (``tele/<role>/<name>``) so stat.json/TB dashboards see the
    same series scrapers do.
    """

    def __init__(self, sample_every: int = 20):
        self.sample_every = sample_every
        self._counters: Dict[str, list] = {}
        self._epoch_t0 = None
        self._last_samples = 0.0
        self._last_gstep = 0

    def before_train(self):
        from distributed_ba3c_tpu import telemetry

        self._epoch_t0 = time.monotonic()
        self._samples_counter = telemetry.registry("learner").counter(
            "train_samples_total"
        )
        self._last_samples = self._samples_counter.value()
        self._last_gstep = self.trainer.global_step

    def trigger_step(self, metrics):
        if metrics is None or self.trainer.global_step % self.sample_every:
            return
        fetched = {k: float(v) for k, v in metrics.items()}
        for k, v in fetched.items():
            self._counters.setdefault(k, []).append(v)

    def trigger_epoch(self):
        from distributed_ba3c_tpu import telemetry

        tr = self.trainer
        holder = tr.stat_holder
        dt = time.monotonic() - self._epoch_t0 if self._epoch_t0 else 0.0
        samples = self._samples_counter.value() - self._last_samples
        self._last_samples += samples
        if not telemetry.enabled():
            # BA3C_TELEMETRY=0: the counters are no-ops — fall back to the
            # loop's own step counter (global_step is loop state, not a
            # metric; no dual accounting re-enters here)
            samples = (tr.global_step - self._last_gstep) * tr.batch_size
        self._last_gstep = tr.global_step
        fps = samples / dt if dt > 0 else 0.0
        holder.add_stat("global_step", tr.global_step)
        holder.add_stat("epoch", tr.epoch_num)
        holder.add_stat("fps", fps)
        for k, vs in self._counters.items():
            if vs:
                holder.add_stat(k, float(np.mean(vs)))
        if tr.score_counter is not None and tr.score_counter.count:
            holder.add_stat("mean_score", tr.score_counter.average)
            holder.add_stat("max_score", tr.score_counter.max)
            tr.last_mean_score = tr.score_counter.average
            tr.score_counter.reset()
        if telemetry.enabled():
            # periodic export: the same series the scrape endpoint serves,
            # folded into stat.json/TB so existing dashboards keep working
            holder.add_stats(telemetry.export_scalars())
        record = holder.finalize()
        logger.info(
            "epoch %d | step %d | fps %.0f | %s",
            tr.epoch_num,
            tr.global_step,
            fps,
            " ".join(
                f"{k}={v:.4g}"
                for k, v in record.items()
                # tele/ series go to stat.json/TB/scrape, not the console
                if k not in ("epoch", "global_step", "fps")
                and not k.startswith("tele/")
            ),
        )
        self._counters = {}
        self._epoch_t0 = time.monotonic()


class ModelSaver(Callback):
    """Save the TrainState every epoch (chief only in multi-host)."""

    def __init__(self, ckpt_dir: Optional[str] = None, max_to_keep: int = 3):
        self.ckpt_dir = ckpt_dir
        self.max_to_keep = max_to_keep

    def before_train(self):
        from distributed_ba3c_tpu.train.checkpoint import CheckpointManager

        d = self.ckpt_dir or os.path.join(
            self.trainer.config.log_dir or ".", "checkpoints"
        )
        # EVERY process gets a manager pointed at the SAME directory: orbax
        # saves are collective in multi-process runs (chief-only saving
        # deadlocks the chief in orbax's barrier). Metadata/pruning are
        # chief-only inside CheckpointManager.
        self.trainer.ckpt_manager = CheckpointManager(
            d, max_to_keep=self.max_to_keep
        )

    def trigger_epoch(self):
        if self.trainer.ckpt_manager is not None:
            path = self.trainer.ckpt_manager.save(
                self.trainer.state, self.trainer.global_step
            )
            from distributed_ba3c_tpu import telemetry

            telemetry.record("checkpoint", step=self.trainer.global_step)
            if self.trainer.is_chief:
                logger.info("saved checkpoint %s", path)


class MaxSaver(Callback):
    """Mark the checkpoint as best when the monitored stat improves.

    Reads the stat named by ``monitor`` from the epoch record StatPrinter
    just finalized (so ``eval_mean_score`` tracks the greedy Evaluator, not
    the sampling-policy mean — reference ``MaxSaver`` kept the Evaluator's
    best, SURVEY.md §2.7 #20). Epochs where the monitored stat is absent
    (e.g. ``--eval_every > 1``) leave the best pointer untouched.
    """

    def __init__(self, monitor: str = "mean_score"):
        self.monitor = monitor

    def trigger_epoch(self):
        tr = self.trainer
        if tr.ckpt_manager is None:
            return
        history = tr.stat_holder.stat_history
        score = history[-1].get(self.monitor) if history else None
        if score is None and self.monitor == "mean_score":
            score = tr.last_mean_score  # pre-StatPrinter wiring fallback
        if score is not None and tr.ckpt_manager.mark_best(
            tr.global_step, score
        ):
            logger.info("new best %s=%.3f", self.monitor, score)


class Evaluator(Callback):
    """Play eval episodes with the current (greedy) policy each epoch.

    Reference: ``Evaluator`` in ``src/common.py`` (SURVEY.md §2.1 #4, §3.5).
    Players run in lockstep so every forward is one batched device call.
    """

    def __init__(self, nr_eval: int, build_player: Callable[[int], object]):
        self.nr_eval = nr_eval
        self.build_player = build_player

    def trigger_epoch(self):
        from distributed_ba3c_tpu.train.eval import eval_model

        mean, mx = eval_model(
            self.trainer.predictor_fn(),
            self.build_player,
            self.nr_eval,
        )
        self.trainer.stat_holder.add_stat("eval_mean_score", mean)
        self.trainer.stat_holder.add_stat("eval_max_score", mx)
        logger.info("eval: mean=%.3f max=%.3f over %d eps", mean, mx, self.nr_eval)
