"""Eval/play: run episodes with the trained policy.

Reference equivalent: ``src/common.py`` — ``play_one_episode``,
``eval_with_funcs``, ``play_n_episodes`` (SURVEY.md §2.1 #4, call stack §3.5).
TPU-native redesign: instead of one thread per eval player each doing a
single-state forward, E players step in lockstep and every forward is one
batched device call.
"""

from __future__ import annotations

from typing import Callable, List, Tuple

import numpy as np


def play_one_episode(
    player, predict: Callable[[np.ndarray], int], max_steps: int = 100000
) -> float:
    """Play a full episode; returns the score. ``predict(state) -> action``."""
    score = 0.0
    for _ in range(max_steps):
        act = predict(player.current_state())
        r, is_over = player.action(act)
        score += r
        if is_over:
            return score
    return score


def eval_model(
    predict_batch: Callable[[np.ndarray], np.ndarray],
    build_player: Callable[[int], object],
    nr_eval: int,
    max_steps: int = 100000,
) -> Tuple[float, float]:
    """Play ``nr_eval`` episodes in lockstep; returns (mean, max) score.

    ``predict_batch(states [E, ...]) -> actions [E]`` (greedy).
    """
    players = [build_player(1000 + i) for i in range(nr_eval)]
    scores = np.zeros(nr_eval)
    done = np.zeros(nr_eval, bool)
    for _ in range(max_steps):
        states = np.stack([p.current_state() for p in players])
        actions = predict_batch(states)
        for i, p in enumerate(players):
            if done[i]:
                continue
            r, over = p.action(int(actions[i]))
            scores[i] += r
            done[i] = done[i] or over
        if done.all():
            break
    return float(scores.mean()), float(scores.max())


def play_n_episodes(
    player, predict: Callable[[np.ndarray], int], nr: int
) -> List[float]:
    """Sequential episode playback (reference ``play_n_episodes``)."""
    return [play_one_episode(player, predict) for _ in range(nr)]
