"""The int8 rung of the quantized rollout/serving ladder.

Per-channel symmetric int8 WEIGHT quantization plus per-tensor activation
scales for the conv/fc forward (the learner always keeps f32 — this is
rollout/serving storage + compute only, exactly like the bf16 rung it
extends, docs/ingest.md). The module split:

- :mod:`spec` — :class:`QuantSpec`, the frozen calibration result: one
  JSON-round-tripping, unknown-field-rejecting document with a stable
  sha256 (the provenance hash every bench row stamps).
- :mod:`qforward` — :func:`quantize_params` (publish-time f32 -> int8
  table) and :func:`make_quant_apply`/:func:`make_quant_fwd_sample`
  (the quantized forward, dequant-free int8 conv where the backend
  compiles it, scale-folded bf16 conv + f32 epilogue where it doesn't).
- :mod:`calibrate` — activation-range capture: :class:`CalibrationTap`
  (the PR-9 shadow-serving tap as a free calibration feed) and the
  offline static-range paths for recorded batches / env rollouts.

Every ``astype``/precision cast of the rollout ladder lives HERE, behind
the audited entries ``predict.server_int8``/``fused.actor_int8`` —
ba3clint rule A16 (unaudited-dtype-cast) holds the rest of the
publish/actor path to that.
"""

from distributed_ba3c_tpu.quantize.calibrate import (
    ActRangeAccumulator,
    CalibrationTap,
    calibrate_from_env,
    calibrate_offline,
)
from distributed_ba3c_tpu.quantize.qforward import (
    QUANT_ARMS,
    int8_conv_supported,
    make_quant_apply,
    make_quant_fwd_sample,
    quant_layer_names,
    quantize_params,
)
from distributed_ba3c_tpu.quantize.spec import QUANT_METHODS, QuantSpec

__all__ = [
    "ActRangeAccumulator",
    "CalibrationTap",
    "QUANT_ARMS",
    "QUANT_METHODS",
    "QuantSpec",
    "calibrate_from_env",
    "calibrate_offline",
    "int8_conv_supported",
    "make_quant_apply",
    "make_quant_fwd_sample",
    "quant_layer_names",
    "quantize_params",
]
