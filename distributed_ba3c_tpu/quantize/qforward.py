"""The quantized serving/actor forward and its publish-time table build.

Two pieces, mirroring the bf16 rung's split exactly:

- :func:`quantize_params` is the PUBLISH step (the overlap prep-cast /
  ``BatchedPredictor._put_policy`` cast, int8 edition): f32 params in,
  int8 serving table out — per-out-channel symmetric weight scales, int8
  kernels, f32 biases, plus the frozen per-tensor activation scale from
  the :class:`~distributed_ba3c_tpu.quantize.spec.QuantSpec`. One small
  jittable pass, amortized over a whole publish interval.
- :func:`make_quant_apply` is the FORWARD: a plain-lax mirror of
  ``BA3CNet.__call__`` built from the shared
  :func:`~distributed_ba3c_tpu.models.a3c.conv_layout` seam (the two
  cannot drift), with two arms:

  * ``int8`` (dequant-free): activations fake-quantize to the int8 grid,
    the conv/dot runs int8 x int8 -> int32 on the MXU-native path
    (``preferred_element_type=int32``), and ONE f32 epilogue folds
    ``act_scale * w_scale`` into the bias add. This is the arm the audit
    entries ``predict.server_int8``/``fused.actor_int8`` pin (T1 proves
    every conv operand is int8).
  * ``folded`` (the no-int8-conv fallback): the conv runs on the int8
    kernel VALUES carried in bf16 (integers <= 127 are exact in bf16)
    with unquantized bf16 activations, and the f32 epilogue applies the
    weight scale — same quantized weights, no int8 compute required.

The policy/value heads and the PReLU stay f32 in both arms (the
models/a3c.py contract): log mu(a|s) keeps its precision and V-trace's
measured-lag correction absorbs the behavior-policy quantization drift.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax import lax

from distributed_ba3c_tpu.models.a3c import PolicyValue, conv_layout
from distributed_ba3c_tpu.quantize.spec import QuantSpec

#: forward arms: ``auto`` resolves per-backend at build time
QUANT_ARMS = ("auto", "int8", "folded")

_DIMENSION_NUMBERS = ("NHWC", "HWIO", "NHWC")

#: cached int8-conv capability probe result per backend
_INT8_CONV_OK: dict = {}


def quant_layer_names(model) -> tuple:
    """The layers the int8 rung quantizes: the conv stack + the big FC
    (``Dense_0``). The heads (``Dense_1``/``Dense_2``) and ``PReLU_0``
    stay f32 — they are tiny, and they own the precision of the
    log-prob/value record V-trace corrects against."""
    return tuple(
        f"Conv_{i}" for i in range(len(conv_layout(model)))
    ) + ("Dense_0",)


def int8_conv_supported(backend: str = "") -> bool:
    """Can this backend compile an int8 x int8 -> int32 conv?

    Probed ONCE per backend with a 1-pixel conv; the result is cached.
    CPU (jax 0.4.37) and TPU both support it; the probe exists so the
    ``auto`` arm degrades to ``folded`` instead of crashing on a backend
    that doesn't."""
    backend = backend or jax.default_backend()
    ok = _INT8_CONV_OK.get(backend)
    if ok is None:
        try:
            x = jnp.zeros((1, 2, 2, 1), jnp.int8)
            w = jnp.zeros((1, 1, 1, 1), jnp.int8)
            jax.jit(
                lambda a, b: lax.conv_general_dilated(
                    a, b, (1, 1), "SAME",
                    dimension_numbers=_DIMENSION_NUMBERS,
                    preferred_element_type=jnp.int32,
                )
            )(x, w).block_until_ready()
            ok = True
        except Exception:
            ok = False
        _INT8_CONV_OK[backend] = ok
    return ok


def _resolve_arm(arm: str) -> str:
    if arm not in QUANT_ARMS:
        raise ValueError(f"quant arm must be one of {QUANT_ARMS}, got {arm!r}")
    if arm == "auto":
        return "int8" if int8_conv_supported() else "folded"
    return arm


def _weight_scale(kernel: jax.Array) -> jax.Array:
    """Per-OUT-CHANNEL symmetric scale: absmax over every other axis,
    mapped so the channel's largest weight lands exactly on +/-127. A
    zero-range channel (all-zero weights — freshly initialized biases'
    neighbors, pruned channels) gets scale 1.0: its quantized weights
    are exactly 0 either way, and the scale stays finite (no NaN/inf
    anywhere downstream)."""
    absmax = jnp.max(jnp.abs(kernel), axis=tuple(range(kernel.ndim - 1)))
    return jnp.where(absmax > 0, absmax / 127.0, 1.0).astype(jnp.float32)


def _quantize_tensor(x: jax.Array, scale) -> jax.Array:
    return jnp.clip(jnp.round(x / scale), -127.0, 127.0).astype(jnp.int8)


def quantize_params(params, spec: QuantSpec):
    """f32 param pytree -> the int8 serving table (jittable; ``spec`` is
    static — close over it or ``functools.partial`` it before jit).

    Quantized layers become ``{kernel_q int8, w_scale f32[co], bias f32,
    act_scale f32[]}``; every other layer (the f32 heads, PReLU) passes
    through untouched. The act scale rides IN the table so the compiled
    forward depends only on avals, never on spec values — one program
    serves every calibration."""
    missing = sorted(set(spec.act_scales) - set(params))
    if missing:
        raise ValueError(
            f"quant spec names layers absent from params: {missing}"
        )
    out = {}
    for name, leaves in params.items():
        if name not in spec.act_scales:
            out[name] = leaves
            continue
        kernel = jnp.asarray(leaves["kernel"], jnp.float32)
        w_scale = _weight_scale(kernel)
        out[name] = {
            "kernel_q": _quantize_tensor(kernel, w_scale),
            "w_scale": w_scale,
            "bias": jnp.asarray(leaves["bias"], jnp.float32),
            "act_scale": jnp.asarray(spec.act_scales[name], jnp.float32),
        }
    return out


def _conv_int8(x: jax.Array, p: dict) -> jax.Array:
    xq = _quantize_tensor(x, p["act_scale"])
    y = lax.conv_general_dilated(
        xq, p["kernel_q"], (1, 1), "SAME",
        dimension_numbers=_DIMENSION_NUMBERS,
        preferred_element_type=jnp.int32,
    )
    # ONE f32 epilogue: int32 accumulator * (s_act * s_w[co]) + bias
    return y.astype(jnp.float32) * (p["act_scale"] * p["w_scale"]) + p["bias"]


def _conv_folded(x: jax.Array, p: dict) -> jax.Array:
    y = lax.conv_general_dilated(
        x.astype(jnp.bfloat16), p["kernel_q"].astype(jnp.bfloat16),
        (1, 1), "SAME",
        dimension_numbers=_DIMENSION_NUMBERS,
        preferred_element_type=jnp.float32,
    )
    return y * p["w_scale"] + p["bias"]


def _dense_int8(x: jax.Array, p: dict) -> jax.Array:
    xq = _quantize_tensor(x, p["act_scale"])
    y = lax.dot_general(
        xq, p["kernel_q"], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    return y.astype(jnp.float32) * (p["act_scale"] * p["w_scale"]) + p["bias"]


def _dense_folded(x: jax.Array, p: dict) -> jax.Array:
    y = lax.dot_general(
        x.astype(jnp.bfloat16), p["kernel_q"].astype(jnp.bfloat16),
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return y * p["w_scale"] + p["bias"]


def make_quant_apply(model, arm: str = "auto") -> Callable:
    """Build ``apply(qparams, states) -> PolicyValue``, the quantized
    mirror of ``model.apply({'params': p}, states)``.

    The layout comes from :func:`conv_layout` — the same triples the f32
    forward executes — so adding/resizing a conv layer updates both
    programs from one place."""
    layout = conv_layout(model)
    arm = _resolve_arm(arm)
    conv = _conv_int8 if arm == "int8" else _conv_folded
    dense = _dense_int8 if arm == "int8" else _dense_folded

    def apply_fn(qparams, state: jax.Array) -> PolicyValue:
        x = state.astype(jnp.float32)
        if state.dtype == jnp.uint8:
            x = x / 255.0
        for i, (_feats, _k, pooled) in enumerate(layout):
            x = nn.relu(conv(x, qparams[f"Conv_{i}"]))
            if pooled:
                x = nn.max_pool(x, window_shape=(2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = dense(x, qparams["Dense_0"])
        alpha = qparams["PReLU_0"]["alpha"].astype(x.dtype)
        x = jnp.where(x >= 0, x, alpha * x)
        logits = x @ qparams["Dense_1"]["kernel"] + qparams["Dense_1"]["bias"]
        value = (x @ qparams["Dense_2"]["kernel"]
                 + qparams["Dense_2"]["bias"])[:, 0]
        return PolicyValue(logits=logits, value=value)

    apply_fn.quant_arm = arm
    return apply_fn


def make_quant_fwd_sample(model, greedy: bool = False,
                          arm: str = "auto") -> Callable:
    """The int8 action server's compiled program: quantized forward + the
    SAME on-device sampling + single-fetch packing contract as
    ``predict.server.make_fwd_sample`` ([3, B] greedy / [4, B] sampling,
    f32) — the scheduler's ``_unpack`` serves either without knowing the
    table's precision. Module-level so the audit registry traces the
    same function the live predictor jits (entry ``predict.server_int8``)."""
    qapply = make_quant_apply(model, arm=arm)

    def fwd_sample(qparams, states, key):
        out = qapply(qparams, states)
        if greedy:
            actions = jnp.argmax(out.logits, axis=-1)
        else:
            actions = jax.random.categorical(key, out.logits, axis=-1)
        actions = actions.astype(jnp.int32)
        log_probs = jax.nn.log_softmax(out.logits, axis=-1)
        logp = jnp.take_along_axis(log_probs, actions[:, None], axis=-1)[:, 0]
        rows = [actions.astype(jnp.float32), out.value, logp]
        if not greedy:
            rows.append(jnp.argmax(out.logits, axis=-1).astype(jnp.float32))
        return jnp.stack(rows)

    fwd_sample.quant_arm = qapply.quant_arm
    return fwd_sample
