"""QuantSpec: the frozen activation-calibration document.

One JSON object pins everything the int8 forward needs beyond the f32
checkpoint itself: per-layer input-activation scales, the method that
produced them and how much traffic it saw. It round-trips losslessly,
rejects unknown fields (the TopologySpec discipline — a typo'd field must
fail loudly, not silently default), and hashes stably, so a bench row
stamped with ``quant_spec_hash`` names EXACTLY one calibration.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import math
from typing import Any, Dict, Mapping, Tuple

#: calibration statistics: ``absmax`` = running max |x| over all served
#: batches; ``percentile`` = running max of per-batch |x| percentiles
#: (clips the activation tail a stray frame would otherwise stretch the
#: whole int8 grid over)
QUANT_METHODS = ("absmax", "percentile")


class QuantSpecError(ValueError):
    """A malformed QuantSpec document (bad JSON, unknown fields, invalid
    scales). ValueError so generic callers still catch it."""


@dataclasses.dataclass(frozen=True)
class QuantSpec:
    """Frozen per-layer activation scales for the int8 forward.

    ``act_scales`` maps quantized layer name (``Conv_0``..``Conv_3``,
    ``Dense_0`` for the flagship net) to the per-tensor symmetric scale
    ``s`` of that layer's INPUT: ``x_q = clip(round(x / s), -127, 127)``.
    Every scale is finite and > 0 by construction — a degenerate
    zero-range calibration freezes to scale 1.0 (calibrate.py), and this
    class re-rejects NaN/inf/non-positive on every load so a corrupt
    file cannot reach the compiled program.
    """

    act_scales: Mapping[str, float]
    method: str = "absmax"
    percentile: float = 99.9
    calibration_batches: int = 0
    calibration_rows: int = 0
    version: int = 1

    def __post_init__(self):
        if self.version != 1:
            raise QuantSpecError(
                f"unknown quant spec version {self.version!r} (this tree "
                "speaks version 1)"
            )
        if self.method not in QUANT_METHODS:
            raise QuantSpecError(
                f"quant method must be one of {QUANT_METHODS}, got "
                f"{self.method!r}"
            )
        if not 0 < self.percentile <= 100:
            raise QuantSpecError(
                f"percentile must be in (0, 100], got {self.percentile}"
            )
        if self.calibration_batches < 0 or self.calibration_rows < 0:
            raise QuantSpecError("calibration counters must be >= 0")
        if not self.act_scales:
            raise QuantSpecError("act_scales must name at least one layer")
        clean: Dict[str, float] = {}
        for name in sorted(self.act_scales):
            s = self.act_scales[name]
            if not isinstance(name, str) or not name:
                raise QuantSpecError(
                    f"act_scales keys must be layer names, got {name!r}"
                )
            if not isinstance(s, (int, float)) or isinstance(s, bool):
                raise QuantSpecError(
                    f"act_scales[{name!r}] must be a number, got {s!r}"
                )
            s = float(s)
            if not math.isfinite(s) or s <= 0:
                raise QuantSpecError(
                    f"act_scales[{name!r}] must be finite and > 0, got {s}"
                )
            clean[name] = s
        object.__setattr__(self, "act_scales", clean)

    # -- identity ----------------------------------------------------------
    @property
    def layers(self) -> Tuple[str, ...]:
        """The quantized layer names, sorted (the forward's loop order is
        fixed by the model layout; this is the membership set)."""
        return tuple(sorted(self.act_scales))

    def sha256(self) -> str:
        """Stable content hash of the CANONICAL serialization (sorted
        keys, compact separators) — the ``quant_spec_hash`` every bench
        row stamps, so two captures are comparable iff the hashes match."""
        canon = json.dumps(
            self.to_doc(), sort_keys=True, separators=(",", ":")
        )
        return hashlib.sha256(canon.encode()).hexdigest()

    # -- (de)serialization -------------------------------------------------
    def to_doc(self) -> Dict[str, Any]:
        return {
            "version": self.version,
            "method": self.method,
            "percentile": self.percentile,
            "calibration_batches": self.calibration_batches,
            "calibration_rows": self.calibration_rows,
            "act_scales": dict(self.act_scales),
        }

    def to_json(self) -> str:
        return json.dumps(self.to_doc(), indent=2, sort_keys=True)

    @classmethod
    def from_doc(cls, doc: Any) -> "QuantSpec":
        if not isinstance(doc, Mapping):
            raise QuantSpecError(
                f"quant spec must be a JSON object, got {type(doc).__name__}"
            )
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = sorted(set(doc) - known)
        if unknown:
            raise QuantSpecError(f"unknown quant spec fields: {unknown}")
        if "act_scales" not in doc:
            raise QuantSpecError("quant spec missing act_scales")
        try:
            return cls(**doc)
        except QuantSpecError:
            raise
        except (TypeError, ValueError) as e:
            raise QuantSpecError(f"bad quant spec: {e}") from None

    @classmethod
    def from_json(cls, text: str) -> "QuantSpec":
        try:
            doc = json.loads(text)
        except json.JSONDecodeError as e:
            raise QuantSpecError(f"quant spec is not valid JSON: {e}")
        return cls.from_doc(doc)

    @classmethod
    def load(cls, path: str) -> "QuantSpec":
        try:
            with open(path) as fh:
                text = fh.read()
        except OSError as e:
            raise QuantSpecError(f"cannot read quant spec: {e}")
        return cls.from_json(text)

    def save(self, path: str) -> None:
        with open(path, "w") as fh:
            fh.write(self.to_json() + "\n")
