"""Activation-scale calibration for the int8 forward.

One accumulator, three feeds:

- :class:`CalibrationTap` — the live-traffic path: a ``shadow_tap``
  (predict/server.py, the PR-9 shadow-serving hook) that observes every
  SERVED batch, accumulates per-layer input absmax/percentile stats, and
  freezes a :class:`QuantSpec` after N batches. Zero new wire machinery:
  calibration is a shadow consumer of the traffic the tier already
  serves.
- :func:`calibrate_offline` — the static-range path over recorded
  batches (an iterable of state arrays), for when there is no live tier.
- :func:`calibrate_from_env` — the no-traffic-at-all fallback the fused
  trainer uses (``--quant_calibrate N`` with ``--overlap``): f32 rollout
  windows through the SAME scan body the actor program runs, feeding the
  visited frame stacks to the accumulator.

Determinism contract (tested): the running statistics are maxima —
permutation-invariant over batches — so the same traffic (same batch
partition) freezes a bit-identical QuantSpec regardless of interleaving.
"""

from __future__ import annotations

from typing import Callable, Iterable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn
from jax import lax

from distributed_ba3c_tpu.models.a3c import conv_layout
from distributed_ba3c_tpu.quantize.qforward import quant_layer_names
from distributed_ba3c_tpu.quantize.spec import QUANT_METHODS, QuantSpec

_DIMENSION_NUMBERS = ("NHWC", "HWIO", "NHWC")


def _make_stats_fn(model, method: str, percentile: float) -> Callable:
    """Build the jitted per-batch statistics forward: an f32 replication
    of the conv stack (the quantized program's own numeric reference —
    deliberately NOT the bf16 training forward) that returns each
    quantized layer's INPUT statistic as one fused device pass."""
    layout = conv_layout(model)

    def stat(x):
        a = jnp.abs(x)
        if method == "absmax":
            return jnp.max(a)
        return jnp.percentile(a, percentile)

    def stats_fn(params, states):
        x = states.astype(jnp.float32)
        if states.dtype == jnp.uint8:
            x = x / 255.0
        out = {}
        for i, (_feats, _k, pooled) in enumerate(layout):
            name = f"Conv_{i}"
            out[name] = stat(x)
            p = params[name]
            x = lax.conv_general_dilated(
                x, jnp.asarray(p["kernel"], jnp.float32), (1, 1), "SAME",
                dimension_numbers=_DIMENSION_NUMBERS,
            ) + jnp.asarray(p["bias"], jnp.float32)
            x = nn.relu(x)
            if pooled:
                x = nn.max_pool(x, window_shape=(2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        out["Dense_0"] = stat(x)
        return out

    return jax.jit(stats_fn)


class ActRangeAccumulator:
    """Running per-layer activation-range statistics -> a QuantSpec.

    ``observe(states)`` folds one batch in (running max of the per-batch
    statistic — for absmax that IS the global absmax; for percentile it
    is the conservative max-of-batch-percentiles, deterministic for a
    given batch partition). ``freeze()`` maps each range to the
    symmetric scale ``range / 127`` with the zero-range -> 1.0 guard.
    """

    def __init__(self, model, params, method: str = "absmax",
                 percentile: float = 99.9):
        if method not in QUANT_METHODS:
            raise ValueError(
                f"method must be one of {QUANT_METHODS}, got {method!r}"
            )
        self._params = params
        self._stats_fn = _make_stats_fn(model, method, percentile)
        self.method = method
        self.percentile = float(percentile)
        self._ranges = {name: 0.0 for name in quant_layer_names(model)}
        self.batches = 0
        self.rows = 0

    def observe(self, states) -> None:
        states = jnp.asarray(states)
        stats = jax.device_get(self._stats_fn(self._params, states))
        for name, v in stats.items():
            v = float(v)
            if np.isfinite(v):  # a NaN frame must not poison the spec
                self._ranges[name] = max(self._ranges[name], v)
        self.batches += 1
        self.rows += int(states.shape[0])

    def freeze(self) -> QuantSpec:
        scales = {
            name: (r / 127.0 if r > 0 else 1.0)
            for name, r in self._ranges.items()
        }
        return QuantSpec(
            act_scales=scales,
            method=self.method,
            percentile=self.percentile,
            calibration_batches=self.batches,
            calibration_rows=self.rows,
        )


class CalibrationTap:
    """A ``shadow_tap`` that calibrates: install on a BatchedPredictor
    (which also mirrors traffic via ``set_shadow``) and every served
    batch feeds the accumulator until ``batches`` are seen; then the
    spec freezes EXACTLY ONCE and ``on_freeze(spec)`` fires — the
    predictor's hook to switch its serving table to int8 in place.

    The tap runs on the scheduler thread (the shadow-fetch path), so
    ``on_freeze`` may safely swap the predictor's compiled program and
    policy table — no dispatch is concurrent with it. Per-batch cost is
    one small jitted stats forward; the overhead test holds it to the
    alternating-reps budget (tests/test_quantize.py).
    """

    def __init__(self, model, params, batches: int,
                 method: str = "absmax", percentile: float = 99.9,
                 on_freeze: Optional[Callable[[QuantSpec], None]] = None,
                 tele_role: Optional[str] = None):
        if batches < 1:
            raise ValueError(f"calibration needs >= 1 batch, got {batches}")
        self._acc = ActRangeAccumulator(
            model, params, method=method, percentile=percentile
        )
        self.batches_target = int(batches)
        self._on_freeze = on_freeze
        self.spec: Optional[QuantSpec] = None
        self._c_batches = self._c_rows = None
        if tele_role is not None:
            from distributed_ba3c_tpu import telemetry

            tele = telemetry.registry(tele_role)
            self._c_batches = tele.counter("quant_calib_batches_total")
            self._c_rows = tele.counter("quant_calib_rows_total")
            tele.gauge(
                "quant_spec_frozen",
                fn=lambda: 1.0 if self.spec is not None else 0.0,
            )

    def __call__(self, states, actions, policy) -> None:
        if self.spec is not None:
            return  # frozen: the tap is inert until uninstalled
        self._acc.observe(states)
        if self._c_batches is not None:
            self._c_batches.inc()
            self._c_rows.inc(int(np.shape(states)[0]))
        if self._acc.batches >= self.batches_target:
            self.spec = self._acc.freeze()
            if self._on_freeze is not None:
                self._on_freeze(self.spec)


def calibrate_offline(model, params, batches: Iterable,
                      method: str = "absmax",
                      percentile: float = 99.9) -> QuantSpec:
    """Static-range calibration over recorded state batches (each item
    one ``[B, H, W, hist]`` array) — the no-live-traffic path."""
    acc = ActRangeAccumulator(
        model, params, method=method, percentile=percentile
    )
    for states in batches:
        acc.observe(states)
    if acc.batches == 0:
        raise ValueError("offline calibration saw zero batches")
    return acc.freeze()


def calibrate_from_env(model, cfg, env, params, key, n_envs: int,
                       batches: int, rollout_len: int = 20,
                       method: str = "absmax",
                       percentile: float = 99.9) -> QuantSpec:
    """Pre-training calibration for the fused/overlap trainer: run
    ``batches`` f32 rollout windows through the SAME scan body the actor
    program executes (fused/loop.py ``make_rollout_body``) from the same
    reset distribution, and feed every visited frame stack in. The spec
    this freezes is what ``--rollout_dtype int8 --quant_calibrate N``
    builds the int8 actor program from."""
    from distributed_ba3c_tpu.fused.loop import make_rollout_body

    if batches < 1:
        raise ValueError(f"calibration needs >= 1 window, got {batches}")
    acc = ActRangeAccumulator(
        model, params, method=method, percentile=percentile
    )
    keys = jax.random.split(key, n_envs)
    env_state = jax.vmap(env.reset)(keys)
    obs = jax.vmap(env.render)(env_state)
    stack = jnp.zeros(
        (n_envs, *obs.shape[1:], cfg.frame_history), jnp.uint8
    ).at[..., -1].set(obs)
    body = make_rollout_body(model, cfg, env, params)
    run = jax.jit(lambda c: lax.scan(body, c, None, length=rollout_len))
    carry = (
        env_state, stack, jax.random.fold_in(key, 1),
        jnp.zeros(n_envs, jnp.float32),
        jnp.zeros(n_envs, jnp.int32),
        jnp.zeros(n_envs, jnp.float32),
    )
    for _ in range(batches):
        carry, traj = run(carry)
        stacks = np.asarray(traj[0])  # [T, B, H, W, hist] uint8
        acc.observe(stacks.reshape(-1, *stacks.shape[2:]))
    return acc.freeze()
