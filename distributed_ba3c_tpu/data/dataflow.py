"""DataFlow abstraction + the train-queue batcher.

Reference equivalents (SURVEY.md §2.4): ``DataFlow.get_data`` generator
protocol, ``BatchData`` (stacks datapoints), ``QueueInput``/``EnqueueThread``
(bridges a flow into the trainer's queue). ``PrefetchDataZMQ`` is not
reproduced as-is: its job (move batching off the hot thread) is done by
``TrainFeed``'s dedicated batcher thread; cross-process prefetch is already
what the simulator plane does.
"""

from __future__ import annotations

import queue
import threading
from abc import ABC, abstractmethod
from typing import Dict, Iterator, List, Optional

import numpy as np

from distributed_ba3c_tpu.utils.concurrency import StoppableThread


class DataFlow(ABC):
    """A restartable stream of datapoints (lists of numpy-compatible items)."""

    @abstractmethod
    def get_data(self) -> Iterator[list]:
        ...

    def size(self) -> int:
        raise NotImplementedError


class QueueDataFlow(DataFlow):
    """Yields datapoints pulled from a (thread-safe) queue.

    Runs until ``stop_event`` is set (forever when none is given) — the
    bounded-timeout get keeps the consuming thread shutdown-responsive
    instead of wedging on a dead producer (ba3clint A2).
    """

    def __init__(
        self,
        q: "queue.Queue[list]",
        stop_event: Optional[threading.Event] = None,
    ):
        self.q = q
        self._stop = stop_event

    def get_data(self) -> Iterator[list]:
        while self._stop is None or not self._stop.is_set():
            try:
                yield self.q.get(timeout=0.5)
            except queue.Empty:
                continue


class BatchData(DataFlow):
    """Stack ``batch_size`` consecutive datapoints along a new leading axis."""

    def __init__(self, ds: DataFlow, batch_size: int):
        self.ds = ds
        self.batch_size = batch_size

    def get_data(self) -> Iterator[List[np.ndarray]]:
        it = self.ds.get_data()
        while True:
            holder = [next(it) for _ in range(self.batch_size)]
            yield [
                np.stack([dp[i] for dp in holder])
                for i in range(len(holder[0]))
            ]


class _BatchFeed:
    """Batcher thread base: item queue → ready stacked batches.

    The learner calls :meth:`next_batch`; a dedicated thread keeps up to
    ``prefetch`` collated batches ready so batch assembly overlaps the device
    step (the reference used an EnqueueThread + TF FIFOQueue for the same
    overlap). Subclasses define :meth:`_collate`.
    """

    def __init__(
        self,
        in_queue: "queue.Queue",
        batch_size: int,
        prefetch: int = 2,
    ):
        self.in_queue = in_queue
        self.batch_size = batch_size
        self._out: "queue.Queue[Dict[str, np.ndarray]]" = queue.Queue(
            maxsize=prefetch
        )
        self._thread = StoppableThread(
            target=self._loop, daemon=True, name=type(self).__name__
        )

    def _collate(self, holder: List) -> Dict[str, np.ndarray]:
        raise NotImplementedError

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._thread.stop()

    def join(self, timeout: Optional[float] = None) -> None:
        """Wait for the batcher thread to exit (it polls with 0.2s timeout)."""
        if self._thread.is_alive():
            self._thread.join(timeout)

    def _loop(self) -> None:
        t = threading.current_thread()
        assert isinstance(t, StoppableThread)
        holder: List = []
        while not t.stopped():
            item = t.queue_get_stoppable(self.in_queue, timeout=0.2)
            if item is None:
                return  # stopped while the actor plane was quiet
            holder.append(item)
            if len(holder) < self.batch_size:
                continue
            batch = self._collate(holder)
            holder = []
            if not t.queue_put_stoppable(self._out, batch, timeout=0.2):
                return  # stopped while the learner was backed up

    def next_batch(self, timeout: Optional[float] = None) -> Dict[str, np.ndarray]:
        return self._out.get(timeout=timeout)

    def qsize(self) -> int:
        return self._out.qsize()


class TrainFeed(_BatchFeed):
    """[state, action, R] datapoints → flat {state, action, return} batches."""

    def _collate(self, holder: List[list]) -> Dict[str, np.ndarray]:
        return {
            "state": np.stack([dp[0] for dp in holder]),
            "action": np.asarray([dp[1] for dp in holder], np.int32),
            "return": np.asarray([dp[2] for dp in holder], np.float32),
        }


class RolloutFeed(_BatchFeed):
    """V-trace segment dicts → time-major [T, B] batches.

    Stacks ``batch_size`` segments from ``VTraceSimulatorMaster`` along a new
    batch axis and transposes time to the front (the reverse-scan layout of
    ops/vtrace.py).
    """

    def _collate(self, holder: List[dict]) -> Dict[str, np.ndarray]:
        batch = {}
        for k in ("state", "action", "reward", "done", "behavior_log_probs"):
            stacked = np.stack([seg[k] for seg in holder], axis=0)  # [B,T,...]
            batch[k] = np.swapaxes(stacked, 0, 1).copy()  # [T,B,...]
        batch["bootstrap_state"] = np.stack(
            [seg["bootstrap_state"] for seg in holder]
        )
        return batch
