"""DataFlow abstraction + the train-queue batcher.

Reference equivalents (SURVEY.md §2.4): ``DataFlow.get_data`` generator
protocol, ``BatchData`` (stacks datapoints), ``QueueInput``/``EnqueueThread``
(bridges a flow into the trainer's queue). ``PrefetchDataZMQ`` is not
reproduced as-is: its job (move batching off the hot thread) is done by
``TrainFeed``'s dedicated batcher thread; cross-process prefetch is already
what the simulator plane does.
"""

from __future__ import annotations

import queue
import threading
import time
from abc import ABC, abstractmethod
from typing import Callable, Dict, Iterator, List, Optional

import numpy as np

from distributed_ba3c_tpu.telemetry.tracing import TraceRef
from distributed_ba3c_tpu.utils.concurrency import StoppableThread


def claim_trace(item):
    """Strip a sampled trace rider off one feed item (tracing.py).

    Masters hand the trace forward as a ``"_trace"`` key on segment dicts
    (V-trace) or a trailing :class:`TraceRef` on ``[state, action, R]``
    datapoint lists (BA3C) — either way it must come OFF before collate
    stacks the item. Returns the ref or None; the item is mutated."""
    if isinstance(item, dict):
        return item.pop("_trace", None)
    if isinstance(item, list) and item and isinstance(item[-1], TraceRef):
        return item.pop()
    return None


class DataFlow(ABC):
    """A restartable stream of datapoints (lists of numpy-compatible items)."""

    @abstractmethod
    def get_data(self) -> Iterator[list]:
        ...

    def size(self) -> int:
        raise NotImplementedError


class QueueDataFlow(DataFlow):
    """Yields datapoints pulled from a (thread-safe) queue.

    Runs until ``stop_event`` is set (forever when none is given) — the
    bounded-timeout get keeps the consuming thread shutdown-responsive
    instead of wedging on a dead producer (ba3clint A2).
    """

    def __init__(
        self,
        q: "queue.Queue[list]",
        stop_event: Optional[threading.Event] = None,
    ):
        self.q = q
        self._stop = stop_event

    def get_data(self) -> Iterator[list]:
        while self._stop is None or not self._stop.is_set():
            try:
                yield self.q.get(timeout=0.5)
            except queue.Empty:
                continue


class BatchData(DataFlow):
    """Stack ``batch_size`` consecutive datapoints along a new leading axis."""

    def __init__(self, ds: DataFlow, batch_size: int):
        self.ds = ds
        self.batch_size = batch_size

    def get_data(self) -> Iterator[List[np.ndarray]]:
        it = self.ds.get_data()
        while True:
            holder = [next(it) for _ in range(self.batch_size)]
            yield [
                np.stack([dp[i] for dp in holder])
                for i in range(len(holder[0]))
            ]


class _BatchFeed:
    """Batcher thread base: item queue → ready stacked batches.

    The learner calls :meth:`next_batch`; a dedicated thread keeps up to
    ``prefetch`` collated batches ready so batch assembly overlaps the device
    step (the reference used an EnqueueThread + TF FIFOQueue for the same
    overlap). Subclasses define :meth:`_collate`.
    """

    #: staging dispatch key into data/staging.py COLLATE_INTO (subclasses)
    _kind: str = ""

    def __init__(
        self,
        in_queue: "queue.Queue",
        batch_size: int,
        prefetch: int = 2,
        staging=None,
    ):
        self.in_queue = in_queue
        self.batch_size = batch_size
        #: data/staging.py HostStagingRing — when set, collate writes
        #: in-place into an acquired slot (ONE obs copy) instead of
        #: allocating fresh arrays per batch; slots recycle behind the
        #: ring's H2D ready fence (docs/ingest.md)
        self.staging = staging
        self._out: "queue.Queue[Dict[str, np.ndarray]]" = queue.Queue(
            maxsize=prefetch
        )
        self._thread = StoppableThread(
            target=self._loop, daemon=True, name=type(self).__name__
        )

    def _collate(self, holder: List) -> Dict[str, np.ndarray]:
        raise NotImplementedError

    def _collate_staged(self, holder: List, t: StoppableThread):
        """Collate into a staging slot (None ONLY on stop — the ring's
        backpressure mirrors the bounded out queue, so a stalled consumer
        pauses the batcher here for as long as it takes, exactly like
        ``queue_put_stoppable``; a transient device stall must never kill
        the one batcher thread the trainer has)."""
        from distributed_ba3c_tpu.data import staging as _staging

        spec_fn, into_fn = _staging.COLLATE_INTO[self._kind]
        slot = _staging.acquire_stoppable(
            self.staging, spec_fn(holder), t.stopped
        )
        if slot is None:
            return None
        into_fn(holder, slot.buffers)
        self.staging.count_staged_copy()
        return self.staging.staged(slot)

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._thread.stop()

    def join(self, timeout: Optional[float] = None) -> None:
        """Wait for the batcher thread to exit (it polls with 0.2s timeout)."""
        if self._thread.is_alive():
            self._thread.join(timeout)

    def _loop(self) -> None:
        t = threading.current_thread()
        assert isinstance(t, StoppableThread)
        holder: List = []
        trace = None  # sampled trace riding the batch being assembled
        while not t.stopped():
            item = t.queue_get_stoppable(self.in_queue, timeout=0.2)
            if item is None:
                return  # stopped while the actor plane was quiet
            ref = claim_trace(item)
            if ref is not None:
                # emit -> drain is the train-queue wait; one trace per
                # batch (a second sampled item in the same holder is
                # stripped but not double-attributed)
                trace = trace or ref.hop("queue_wait", "learner")
            holder.append(item)
            if len(holder) < self.batch_size:
                continue
            if self.staging is not None:
                batch = self._collate_staged(holder, t)
                if batch is None:
                    return  # stopped while every staging slot was fenced
            else:
                batch = self._collate(holder)
            holder = []
            if trace is not None:
                ref = trace.hop("collate", "learner")
                # StagedBatch carries the ref as an attribute — device_put
                # must never meet a TraceRef (train/trainer.py contract)
                if self.staging is not None:
                    batch.trace = ref
                else:
                    batch["_trace"] = ref
                trace = None
            if not t.queue_put_stoppable(self._out, batch, timeout=0.2):
                if self.staging is not None:
                    batch.release()  # slot back in rotation for the join
                return  # stopped while the learner was backed up

    def next_batch(self, timeout: Optional[float] = None) -> Dict[str, np.ndarray]:
        return self._out.get(timeout=timeout)

    def qsize(self) -> int:
        return self._out.qsize()


def collate_train(holder: List[list]) -> Dict[str, np.ndarray]:
    """[state, action, R] datapoints → flat {state, action, return} arrays
    (THE collate both :class:`TrainFeed` and the multi-fleet merge use —
    one definition, or the two streams' batch layouts could drift).

    This is the COMPAT path: it allocates fresh arrays and pays one obs
    stack pass per batch (self-reported to ``ingest_copies_total``); the
    staged path (data/staging.py collate_train_into) writes the same
    bytes once into a reused slot."""
    from distributed_ba3c_tpu.data.staging import count_legacy_copies

    count_legacy_copies(1.0)
    return {
        # sanctioned compat copy — the staged collate is the budget path
        "state": np.stack([dp[0] for dp in holder]),  # ba3clint: disable=A13
        "action": np.asarray([dp[1] for dp in holder], np.int32),
        "return": np.asarray([dp[2] for dp in holder], np.float32),
    }


def collate_rollout(holder: List[dict]) -> Dict[str, np.ndarray]:
    """V-trace segment dicts → time-major [T, B] arrays (shared by
    :class:`RolloutFeed`, the multi-fleet merge AND the pod experience
    shipper, like collate_train). ``behavior_values`` rides along when the
    emitting master records it (pod/host.py PodSimulatorMaster — the
    ``value_lag_mae`` input); the V-trace planes' segments simply lack the
    key and their batch layout is unchanged.

    COMPAT path, copy-accounted like :func:`collate_train`: the obs bytes
    pay a coercion pass (lazy ``SegStates`` columns), a stack pass and
    the time-major ``.copy()`` — 3 passes per batch vs the staged
    collate's 1 (the ``plane_bench --ingest`` before/after evidence)."""
    from distributed_ba3c_tpu.data.staging import count_legacy_copies

    lazy = hasattr(holder[0]["state"], "materialize_into")
    count_legacy_copies(3.0 if lazy else 2.0)
    batch = {}
    keys = ("state", "action", "reward", "done", "behavior_log_probs")
    if "behavior_values" in holder[0]:
        keys += ("behavior_values",)
    for k in keys:
        # sanctioned compat copies — the staged collate is the budget path
        stacked = np.stack([seg[k] for seg in holder], axis=0)  # ba3clint: disable=A13 — [B,T,...]
        batch[k] = np.swapaxes(stacked, 0, 1).copy()  # ba3clint: disable=A13 — [T,B,...]
    batch["bootstrap_state"] = np.stack(  # ba3clint: disable=A13
        [seg["bootstrap_state"] for seg in holder]
    )
    return batch


class TrainFeed(_BatchFeed):
    """[state, action, R] datapoints → flat {state, action, return} batches."""

    _kind = "train"

    def _collate(self, holder: List[list]) -> Dict[str, np.ndarray]:
        return collate_train(holder)


class RolloutFeed(_BatchFeed):
    """V-trace segment dicts → time-major [T, B] batches.

    Stacks ``batch_size`` segments from ``VTraceSimulatorMaster`` along a new
    batch axis and transposes time to the front (the reverse-scan layout of
    ops/vtrace.py).
    """

    _kind = "rollout"

    def _collate(self, holder: List[dict]) -> Dict[str, np.ndarray]:
        return collate_rollout(holder)


class FleetMergeFeed:
    """K per-fleet queues → one merged train stream (docs/actor_plane.md).

    The multi-fleet macro-batching collator: each fleet's master emits into
    its own (Fast)queue, and this feed drains all K with a FAIR ROUND-ROBIN
    — at most one item per fleet per pass, skipping empty queues — into
    per-fleet holders. Fairness is what keeps one slow fleet from wedging
    the drain order (the fast fleets' queues keep emptying — their bounded-
    queue backpressure engages only when their own sub-batch is already
    banked) and one fast fleet from crowding a slow one out of the stream.

    Two output shapes, same ``next_batch`` contract as :class:`_BatchFeed`:

    - ``stacked=True`` (macro-batching, the default): a batch is ready when
      EVERY fleet banked ``batch_size`` of its own items; per-fleet
      sub-batches are collated separately and stacked on a new leading
      fleet axis — ``{k: [K, ...]}`` — exactly the layout the macro steps
      (parallel/train_step.py make_macro_train_step and friends) shard
      fleet-major over the mesh. A dead fleet therefore stalls the stream
      (the learner's feed timeout turns that into a loud failure, same as
      a dead single-fleet plane).
    - ``stacked=False``: items interleave round-robin into one flat
      ``batch_size`` batch (the single-stream ``feed_batch`` contract) —
      the merge shape for a learner that wants fleet-blind batches.

    Ring-safety contract (utils/shm.py): each fleet's holder pins at most
    ``batch_size`` of ITS OWN ring views between collates (stacked mode),
    so every fleet master's ``feed_batch`` must be set to this feed's
    ``batch_size`` — same declaration TrainFeed call sites make.
    """

    _POLL_S = 0.002

    def __init__(
        self,
        queues: List["queue.Queue"],
        batch_size: int,
        collate: "Callable[[List], Dict[str, np.ndarray]]" = collate_train,
        stacked: bool = True,
        prefetch: int = 2,
        staging=None,
    ):
        if not queues:
            raise ValueError("FleetMergeFeed needs at least one fleet queue")
        self.queues = list(queues)
        self.batch_size = batch_size
        self.stacked = stacked
        self._collate_one = collate
        #: staged macro collate: each fleet's sub-batch writes in-place
        #: into its ``[k]`` stripe of one [K, ...] staging slot — the
        #: per-sub collate AND the fleet stack collapse into one pass
        self.staging = staging
        self._kind = "rollout" if collate is collate_rollout else "train"
        self._out: "queue.Queue[Dict[str, np.ndarray]]" = queue.Queue(
            maxsize=prefetch
        )
        self._thread = StoppableThread(
            target=self._loop, daemon=True, name=type(self).__name__
        )

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._thread.stop()

    def join(self, timeout: Optional[float] = None) -> None:
        if self._thread.is_alive():
            self._thread.join(timeout)

    def next_batch(self, timeout: Optional[float] = None) -> Dict[str, np.ndarray]:
        return self._out.get(timeout=timeout)

    def qsize(self) -> int:
        return self._out.qsize()

    def _loop(self) -> None:
        t = threading.current_thread()
        assert isinstance(t, StoppableThread)
        K, B = len(self.queues), self.batch_size
        holders: List[list] = [[] for _ in range(K)]
        flat: list = []
        trace = None  # sampled trace riding the macro-batch being banked
        rr = 0  # flat mode: fleet owed the next slot (round-robin cursor)
        while not t.stopped():
            drew = False
            order = [(rr + off) % K for off in range(K)]  # freeze this pass
            for k in order:
                if self.stacked and len(holders[k]) >= B:
                    continue  # sub-batch banked: leave backpressure to act
                try:
                    item = self.queues[k].get_nowait()
                except queue.Empty:
                    continue
                drew = True
                ref = claim_trace(item)
                if ref is not None:
                    trace = trace or ref.hop("queue_wait", "learner")
                if self.stacked:
                    holders[k].append(item)
                else:
                    flat.append(item)
                    rr = (k + 1) % K  # next pass starts past the last draw
                    if len(flat) == B:
                        out = self._flat_collate(flat, t)
                        if out is None:
                            return  # stopped mid-staging-acquire
                        flat = []
                        if trace is not None:
                            ref = trace.hop("collate", "learner")
                            if self.staging is not None:
                                out.trace = ref
                            else:
                                out["_trace"] = ref
                            trace = None
                        if not t.queue_put_stoppable(
                            self._out, out, timeout=0.2
                        ):
                            if self.staging is not None:
                                out.release()
                            return
            if self.stacked and all(len(h) == B for h in holders):
                batch = self._stacked_collate(holders, t)
                if batch is None:
                    return  # stopped mid-staging-acquire
                holders = [[] for _ in range(K)]
                if trace is not None:
                    ref = trace.hop("collate", "learner")
                    if self.staging is not None:
                        batch.trace = ref
                    else:
                        batch["_trace"] = ref
                    trace = None
                if not t.queue_put_stoppable(self._out, batch, timeout=0.2):
                    if self.staging is not None:
                        batch.release()
                    return
            if not drew:
                # every queue empty (or banked full): bounded sleep-poll,
                # the FastQueue idiom — never a condvar wait on K queues
                time.sleep(self._POLL_S)

    def _flat_collate(self, flat: list, t: StoppableThread):
        """One interleaved batch (``stacked=False``) — staged when a ring
        is attached, the shared collate otherwise."""
        if self.staging is None:
            return self._collate_one(flat)
        from distributed_ba3c_tpu.data import staging as _staging

        spec_fn, into_fn = _staging.COLLATE_INTO[self._kind]
        slot = _staging.acquire_stoppable(
            self.staging, spec_fn(flat), t.stopped
        )
        if slot is None:
            return None
        into_fn(flat, slot.buffers)
        self.staging.count_staged_copy()
        return self.staging.staged(slot)

    def _stacked_collate(self, holders: List[list], t: StoppableThread):
        """One [K, ...] macro batch. Staged mode collapses the per-fleet
        collate AND the fleet-axis stack into one pass: each sub-batch
        writes in-place into its ``[k]`` stripe of the slot."""
        if self.staging is None:
            from distributed_ba3c_tpu.data.staging import count_legacy_copies

            subs = [self._collate_one(h) for h in holders]
            # the fleet-axis stack is one MORE pass over bytes the K
            # sub-collates already counted as K blocks — report the pass
            # without a new block so the legacy ratio stays > 1
            count_legacy_copies(1.0, blocks=0)
            return {
                # sanctioned compat copy: the fleet-axis stack (the staged
                # macro collate writes stripes in place instead)
                key: np.stack([s[key] for s in subs])  # ba3clint: disable=A13
                for key in subs[0]
            }
        from distributed_ba3c_tpu.data import staging as _staging

        spec_fn, into_fn = _staging.COLLATE_INTO[self._kind]
        sub_spec = spec_fn(holders[0])
        spec = {
            key: ((len(holders), *shape), dtype)
            for key, (shape, dtype) in sub_spec.items()
        }
        slot = _staging.acquire_stoppable(self.staging, spec, t.stopped)
        if slot is None:
            return None
        for k, h in enumerate(holders):
            into_fn(h, {key: buf[k] for key, buf in slot.buffers.items()})
        self.staging.count_staged_copy()
        return self.staging.staged(slot)
