"""Data plane: datapoint flows and the queue→batch bridge to the learner.

Reference equivalent: ``tensorpack/dataflow/`` + ``QueueInput`` (SURVEY.md
§2.4 #11-12). The reference's generator-of-datapoints + TF FIFOQueue pipeline
becomes: a bounded host queue filled by the master, a batcher thread whose
collate writes uint8 datapoints IN PLACE into a pinned staging ring (one
host copy per block, ``data/staging.py``), and a ``DeviceIngest`` pipeline
that dispatches the next batch's H2D behind the running step
(docs/ingest.md; the legacy stack-and-device_put chain survives as the
measured compat foil).
"""

from distributed_ba3c_tpu.data.dataflow import (
    BatchData,
    DataFlow,
    QueueDataFlow,
    RolloutFeed,
    TrainFeed,
)
from distributed_ba3c_tpu.data.staging import (
    BlockStager,
    DeviceIngest,
    HostStagingRing,
)

__all__ = [
    "BatchData",
    "BlockStager",
    "DataFlow",
    "DeviceIngest",
    "HostStagingRing",
    "QueueDataFlow",
    "RolloutFeed",
    "TrainFeed",
]
