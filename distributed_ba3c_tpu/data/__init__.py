"""Data plane: datapoint flows and the queue→batch bridge to the learner.

Reference equivalent: ``tensorpack/dataflow/`` + ``QueueInput`` (SURVEY.md
§2.4 #11-12). The reference's generator-of-datapoints + TF FIFOQueue pipeline
becomes: a bounded host queue filled by the master, a batcher thread stacking
uint8 datapoints, and (in the trainer) async device_put against the mesh
sharding so H2D overlaps compute.
"""

from distributed_ba3c_tpu.data.dataflow import (
    BatchData,
    DataFlow,
    QueueDataFlow,
    RolloutFeed,
    TrainFeed,
)

__all__ = ["BatchData", "DataFlow", "QueueDataFlow", "RolloutFeed", "TrainFeed"]
