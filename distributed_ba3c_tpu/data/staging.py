"""Device-ingest staging: one host copy, H2D overlapped behind the learner.

The ingest chain used to move every observation byte across the host THREE
times before a program saw it — shm-ring window → segment ``np.stack`` at
flush → collate's stack + time-major ``.copy()`` — and then ``device_put``
at the head of the step, synchronous with everything the learner was about
to do. This module is the replacement (docs/ingest.md):

- :class:`HostStagingRing`: a small ring (double-buffered by default) of
  PREALLOCATED contiguous staging arrays shaped like one collated batch.
  The feeds' in-place collates (:func:`collate_train_into` /
  :func:`collate_rollout_into`) write obs bytes from the shm-ring views
  (or block-wire frames) straight into a ring slot — ONE host copy per
  ingested block, counted by ``ingest_copies_total`` so the budget is a
  measured number, not a claim (``plane_bench --ingest`` gates it at
  exactly 1.0).
- **Donation-safety fence**: a slot whose buffers were handed to
  ``device_put`` is not writable again until every device array produced
  from it reports ready — the H2D transfer has consumed the host bytes.
  Reusing the buffer earlier would be the host-side read-after-donate
  (the J5 hazard, transfer edition); ``acquire`` pays the wait (measured:
  ``staging_wait_s`` + the ``staging_wait`` span) instead of corrupting
  an in-flight transfer. The regression test overwrites a slot right
  after the fence opens and asserts the device batch kept its bytes.
- :class:`DeviceIngest`: the async-H2D pipeline. The trainer claims batch
  k's device arrays (already dispatched), runs the step, then calls
  :meth:`DeviceIngest.prefetch` — which dispatches the H2D for batch k+1
  while the device is busy with step k. The overlap split / pod learner
  give the copy a program to hide behind; the ``h2d_copy`` span is where
  the moved cost shows up (it left the step's critical path, it did not
  disappear).
- :class:`BlockStager`: the pod learner's shape-keyed variant — reuses
  one staging TrajBlock per [T, B] shape instead of seven fresh
  ``np.ascontiguousarray`` allocations per shipped block, with the same
  ready fence and copy accounting. ``copy_in`` may run on the ingest
  receive thread (pod/ingest.py) so the wire→staging write overlaps the
  learner's step; ``to_device`` runs on the learner thread after the
  staleness gate (a rejected block cancels its slot without a transfer).

Copy accounting contract (the ``plane_bench --ingest`` measurand): the
``ingest_copies_total`` counter counts FULL PASSES over one collated
batch's obs bytes on the train-ingest path, ``ingest_blocks_total``
counts collated batches — copies-per-block is their ratio. The staged
path increments exactly 1.0 per batch (the staging write); the legacy
collates self-report their stack/transpose passes. H2D transfers are not
host copies and are never counted.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from distributed_ba3c_tpu import telemetry

#: spec: key -> (shape, dtype) of one collated batch's arrays
Spec = Dict[str, Tuple[tuple, Any]]

#: default slot count: prefetch-queue depth (2) + one filling + one
#: in-flight transfer — enough that a healthy pipeline never waits on the
#: fence, small enough that backpressure reaches the batcher thread (the
#: shm-ring cap contract counts the feed holder, not this ring: staged
#: slots hold COPIES, never ring views)
DEFAULT_SLOTS = 4


def _counters(tele_role: str):
    tele = telemetry.registry(tele_role)
    return (
        tele.counter("ingest_copies_total"),
        tele.counter("ingest_blocks_total"),
    )


def count_legacy_copies(
    passes: float, tele_role: str = "learner", blocks: int = 1
) -> None:
    """Self-report of a legacy (non-staged) collate: ``passes`` full
    passes over one batch's obs bytes, ``blocks`` batches (0 for an
    EXTRA pass on already-counted batches — the fleet-axis stack). ONE
    call per site — the copy budget must stay a per-batch ratio."""
    c_copies, c_blocks = _counters(tele_role)
    c_copies.inc(passes)
    if blocks:
        c_blocks.inc(blocks)


class _Slot:
    """One staging slot: preallocated buffers + the fence state."""

    __slots__ = ("buffers", "handles", "index")

    def __init__(self, buffers: Dict[str, np.ndarray], index: int):
        self.buffers = buffers
        self.handles: Optional[list] = None  # device arrays from last H2D
        self.index = index


class StagedBatch(dict):
    """A collated batch living in a staging slot (dict of the slot's
    buffers, so every legacy ``batch[k]`` consumer works unchanged).
    ``trace`` rides as an attribute, never a dict key — ``device_put``
    must not meet a TraceRef. Consumers MUST resolve the slot: either
    :meth:`DeviceIngest` dispatch (which calls ``ring.dispatched``) or
    ``release()`` when the batch is abandoned."""

    def __init__(self, buffers, slot: _Slot, ring: "HostStagingRing"):
        super().__init__(buffers)
        self.slot = slot
        self.ring = ring
        self.trace = None

    def release(self) -> None:
        self.ring.release(self.slot)


def _ready(handle) -> bool:
    fn = getattr(handle, "is_ready", None)
    return fn() if fn is not None else True


_DEALIAS = None


def _dealias_fn():
    """Backend-dependent de-alias pass for staged puts.

    On TPU/GPU, ``device_put`` is a real DMA into device memory — the
    host buffer is consumed when the transfer resolves, so the ready
    fence is exactly right and this returns None (no extra pass). The
    CPU PJRT client instead ZERO-COPIES suitably-aligned numpy buffers:
    the "device" array aliases the staging slot forever, and reusing the
    slot would rewrite data a later consumer still reads (the staging
    fence test caught this live). There, the transfer is materialized as
    one device-side copy — fencing on the COPY's output is sound even
    when the put aliased, because output-ready implies the read of the
    slot finished."""
    global _DEALIAS
    if _DEALIAS is None:
        import jax

        if jax.default_backend() == "cpu":
            _DEALIAS = jax.jit(lambda x: x.copy())
        else:
            _DEALIAS = False
    return _DEALIAS or None


class HostStagingRing:
    """N preallocated staging slots with the ready fence.

    Single producer (the feed's batcher thread) acquires; a single
    consumer (the trainer / DeviceIngest) attaches device handles after
    dispatch or releases. The spec is adopted from the first ``acquire``
    — a mid-run spec change (new key set / shapes) reallocates and is
    counted (``staging_realloc_total``): batch shapes are ONE warmed
    shape per run (the audit tripwire's contract), so a nonzero realloc
    count is itself a finding.
    """

    def __init__(self, slots: int = DEFAULT_SLOTS, tele_role: str = "learner"):
        self._n = max(2, int(slots))
        self._slots: List[_Slot] = []
        self._spec: Optional[Spec] = None
        self._cursor = 0
        self._lock = threading.Lock()
        self._free = threading.Condition(self._lock)
        self._busy: set = set()  # slot indices acquired or queued, unfenced
        self.tele_role = tele_role
        tele = telemetry.registry(tele_role)
        self._c_copies, self._c_blocks = _counters(tele_role)
        self._c_waits = tele.counter("staging_waits_total")
        self._c_realloc = tele.counter("staging_realloc_total")
        self._h_wait = tele.histogram("staging_wait_s", unit=1e-6)
        # weakref-backed fn gauge (the predict/server.py idiom): the
        # process-global registry must not pin an abandoned ring's
        # preallocated buffers for the life of the process
        import weakref

        ref = weakref.ref(self)
        tele.gauge(
            "staging_slots",
            fn=lambda: len(r._slots) if (r := ref()) else 0,
        )

    # -- allocation --------------------------------------------------------
    def _alloc(self, spec: Spec) -> None:
        self._slots = [
            _Slot(
                {k: np.zeros(shape, dtype) for k, (shape, dtype) in spec.items()},
                i,
            )
            for i in range(self._n)
        ]
        self._spec = dict(spec)
        self._busy.clear()
        self._cursor = 0

    # -- producer side -----------------------------------------------------
    def acquire(
        self,
        spec: Spec,
        timeout: Optional[float] = None,
        stop: Optional[Callable[[], bool]] = None,
    ) -> Optional[_Slot]:
        """The next writable slot, or None on timeout/stop.

        Blocks (bounded) while every slot is either queued downstream or
        still being consumed by an in-flight H2D transfer — that wait IS
        the ring's backpressure, mirroring the bounded prefetch queue —
        and fences the chosen slot: its previous dispatch's device arrays
        must all report ready before the buffers are handed back."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            if self._spec != spec:
                if self._spec is not None:
                    self._c_realloc.inc()
                self._alloc(spec)
            t0 = time.monotonic()
            waited = False
            while True:
                slot = self._next_free_locked()
                if slot is not None:
                    break
                waited = True
                remaining = 0.05
                if deadline is not None:
                    remaining = min(remaining, deadline - time.monotonic())
                    if remaining <= 0:
                        return None
                self._free.wait(remaining)
                if stop is not None and stop():
                    return None
            if waited:
                self._c_waits.inc()
            self._h_wait.observe(time.monotonic() - t0)
            self._busy.add(slot.index)
            slot.handles = None
            return slot

    def _next_free_locked(self) -> Optional[_Slot]:
        """First slot that is not downstream AND whose fence is open."""
        for off in range(len(self._slots)):
            slot = self._slots[(self._cursor + off) % len(self._slots)]
            if slot.index in self._busy:
                continue
            if slot.handles is not None and not all(
                _ready(h) for h in slot.handles
            ):
                continue  # H2D still consuming the host bytes
            self._cursor = (slot.index + 1) % len(self._slots)
            return slot
        return None

    def staged(self, slot: _Slot) -> StagedBatch:
        """Wrap an acquired (and now filled) slot for the out queue; the
        in-place collates already counted the write."""
        return StagedBatch(slot.buffers, slot, self)

    def count_staged_copy(self) -> None:
        """The ONE host copy of a staged batch (called by the in-place
        collates, once per batch)."""
        self._c_copies.inc(1.0)
        self._c_blocks.inc()

    # -- consumer side -----------------------------------------------------
    def _owns(self, slot: _Slot) -> bool:
        """This slot belongs to the CURRENT ring generation. A mid-run
        spec realloc replaces the slot list; a pre-realloc StagedBatch
        resolving afterwards must not touch the new generation's
        bookkeeping — its index could name a live new slot, and freeing
        that would let the producer overwrite a queued batch's bytes."""
        return (
            slot.index < len(self._slots)
            and self._slots[slot.index] is slot
        )

    def dispatched(self, slot: _Slot, handles: list) -> None:
        """H2D dispatched for this slot: record the fence handles and put
        the slot back in rotation (writable once the transfer resolves)."""
        with self._lock:
            if not self._owns(slot):
                return  # stale pre-realloc slot: orphaned, GC owns it
            slot.handles = list(handles)
            self._busy.discard(slot.index)
            self._free.notify_all()

    def release(self, slot: _Slot) -> None:
        """Return a slot without a dispatch (shutdown / abandoned batch)."""
        with self._lock:
            if not self._owns(slot):
                return  # stale pre-realloc slot: orphaned, GC owns it
            slot.handles = None
            self._busy.discard(slot.index)
            self._free.notify_all()


# --------------------------------------------------------------------------
# specs + in-place collates (byte-exact vs data/dataflow.py collate_*)
# --------------------------------------------------------------------------


def train_spec(holder: List[list]) -> Spec:
    """Spec of ``collate_train``'s output for this holder (shapes read off
    the items — no materialization)."""
    state = holder[0][0]
    b = len(holder)
    return {
        "state": ((b, *np.shape(state)), getattr(state, "dtype", np.uint8)),
        "action": ((b,), np.int32),
        "return": ((b,), np.float32),
    }


def rollout_spec(holder: List[dict]) -> Spec:
    """Spec of ``collate_rollout``'s output (time-major [T, B] layout)."""
    seg = holder[0]
    b = len(holder)
    t = len(seg["action"])
    state = seg["state"]  # SegStates or [T, ...] ndarray — both have .shape
    boot = seg["bootstrap_state"]
    spec: Spec = {
        "state": (
            (t, b, *tuple(state.shape)[1:]),
            getattr(state, "dtype", np.uint8),
        ),
        "action": ((t, b), np.int32),
        "reward": ((t, b), np.float32),
        "done": ((t, b), np.float32),
        "behavior_log_probs": ((t, b), np.float32),
        "bootstrap_state": ((b, *np.shape(boot)), getattr(boot, "dtype", np.uint8)),
    }
    if "behavior_values" in seg:
        spec["behavior_values"] = ((t, b), np.float32)
    return spec


def _write_states(dest: np.ndarray, src) -> None:
    """One obs write: lazy sources interleave straight into ``dest``."""
    mi = getattr(src, "materialize_into", None)
    if mi is not None:
        mi(dest)
    else:
        dest[...] = src


def collate_train_into(holder: List[list], out: Dict[str, np.ndarray]) -> None:
    """In-place :func:`~distributed_ba3c_tpu.data.dataflow.collate_train`:
    byte-exact same values, written into preallocated ``out`` arrays —
    the ring-view rows' ONE copy is the staging write."""
    state_out = out["state"]
    action_out = out["action"]
    return_out = out["return"]
    for i, dp in enumerate(holder):
        _write_states(state_out[i], dp[0])
        action_out[i] = dp[1]
        return_out[i] = dp[2]


def collate_rollout_into(holder: List[dict], out: Dict[str, np.ndarray]) -> None:
    """In-place :func:`~distributed_ba3c_tpu.data.dataflow.collate_rollout`:
    same time-major [T, B] values, one obs pass — each segment's (lazy)
    state column interleaves directly into its ``out["state"][:, i]``
    stripe, never through an intermediate stack."""
    keys = ("action", "reward", "done", "behavior_log_probs")
    if "behavior_values" in holder[0]:
        keys += ("behavior_values",)
    state_out = out["state"]
    boot_out = out["bootstrap_state"]
    for i, seg in enumerate(holder):
        _write_states(state_out[:, i], seg["state"])
        _write_states(boot_out[i], seg["bootstrap_state"])
        for k in keys:
            out[k][:, i] = seg[k]


#: legacy-collate → in-place variant (the feeds' staging dispatch table)
COLLATE_INTO: Dict[str, Tuple[Callable, Callable]] = {
    "train": (train_spec, collate_train_into),
    "rollout": (rollout_spec, collate_rollout_into),
}


def acquire_stoppable(
    ring: "HostStagingRing", spec: Spec, stopped: Callable[[], bool]
) -> Optional["_Slot"]:
    """Acquire that returns None ONLY on stop — the feeds' batcher-thread
    shape (the ``queue_put_stoppable`` idiom). A transient consumer stall
    longer than any fixed timeout must pause the batcher, never kill it:
    each bounded acquire that comes back empty logs once per long stall
    (flight-recorded) and retries until the thread is told to stop."""
    stalls = 0
    while not stopped():
        slot = ring.acquire(spec, timeout=5.0, stop=stopped)
        if slot is not None:
            return slot
        stalls += 1
        if stalls == 1 or stalls % 12 == 0:  # first, then ~once a minute
            telemetry.record(
                "staging_acquire_stall",
                role=ring.tele_role,
                waited_s=5.0 * stalls,
            )
    return None


def device_put_staged(value: np.ndarray, sharding=None):
    """THE put for staged (reused) host buffers: an async transfer whose
    readiness genuinely means "the host bytes were consumed" on every
    backend (see :func:`_dealias_fn`). Fence slot reuse on ITS outputs,
    never on a raw ``device_put``'s."""
    import jax

    if jax.process_count() > 1 and sharding is not None:
        out = jax.make_array_from_process_local_data(sharding, value)
    elif sharding is not None:
        out = jax.device_put(value, sharding)
    else:
        out = jax.device_put(value)
    dealias = _dealias_fn()
    if dealias is not None:
        out = dealias(out)
    return out


# --------------------------------------------------------------------------
# the async-H2D pipeline
# --------------------------------------------------------------------------


class DeviceIngest:
    """Feed → device arrays, with the k+1 transfer hidden behind step k.

    Wraps a feed (``next_batch``/``start``/``stop``/``join``/``qsize``)
    and owns the device side of the staging contract:

    - :meth:`next_batch` returns ``{key: device_array, ["_trace"]: ref}``
      — the staged pipeline's replacement for the trainer's per-key
      ``device_put`` at the head of the step. If a prefetched batch is
      pending it is returned instantly (its H2D was dispatched behind the
      previous step); otherwise the fetch+dispatch happens now.
    - :meth:`prefetch` (call it right AFTER dispatching the learner step)
      takes whatever batch the feed has ready — non-blocking, so a quiet
      actor plane never stalls the step loop — and dispatches its H2D
      while the device executes. This is the overlap the trainer's old
      post-step staging fetch wanted but could not have (a BLOCKING fetch
      starves at shutdown; a non-blocking one cannot).

    ``sharding`` is the step's batch sharding (dict per key, or one for
    all); multi-host processes feed their local rows through
    ``make_array_from_process_local_data`` exactly like the legacy path.
    """

    is_device_ingest = True

    def __init__(self, feed, sharding, tele_role: str = "learner"):
        self.feed = feed
        self._sharding = sharding
        self._staged: Optional[Tuple[dict, Any]] = None
        self.tele_role = tele_role
        tele = telemetry.registry(tele_role)
        self._c_prefetched = tele.counter("ingest_prefetched_total")
        self._c_dispatch_now = tele.counter("ingest_dispatch_now_total")
        self._h_claim = tele.histogram("ingest_claim_s", unit=1e-6)

    # -- feed facade -------------------------------------------------------
    def start(self) -> None:
        self.feed.start()

    def stop(self) -> None:
        self.feed.stop()
        # a held prefetched batch never reaches a step: drop the
        # reference — its slot went back into rotation at dispatch (the
        # fence handles were attached there), so nothing leaks
        self._staged = None

    def join(self, timeout: Optional[float] = None) -> None:
        self.feed.join(timeout)

    def qsize(self) -> int:
        return self.feed.qsize()

    # -- device side -------------------------------------------------------
    def _put(self, key: str, value: np.ndarray):
        sh = (
            self._sharding[key]
            if isinstance(self._sharding, dict)
            else self._sharding
        )
        return device_put_staged(value, sh)

    def _dispatch(self, batch) -> Tuple[dict, Any]:
        """Issue the H2D transfers for one host batch (async); returns
        (device dict, trace)."""
        if isinstance(batch, StagedBatch):
            trace = batch.trace
            out = {k: self._put(k, v) for k, v in batch.items()}
            # fence handles: the slot becomes writable only when every
            # transfer has consumed the host bytes (donation safety)
            batch.ring.dispatched(batch.slot, list(out.values()))
        else:  # plain dict from a non-staged feed (compat path)
            trace = batch.pop("_trace", None)
            out = {k: self._put(k, v) for k, v in batch.items()}
        if trace is not None:
            trace = trace.hop("h2d_copy", self.tele_role)
        return out, trace

    def prefetch(self) -> bool:
        """Dispatch the NEXT batch's H2D if the feed has one ready now.
        Non-blocking; returns True when a batch is staged in flight."""
        if self._staged is not None:
            return True
        import queue as _queue

        try:
            batch = self.feed.next_batch(timeout=0.0)
        except _queue.Empty:
            return False
        if batch is None:
            return False
        self._staged = self._dispatch(batch)
        self._c_prefetched.inc()
        return True

    def next_batch(self, timeout: Optional[float] = None) -> dict:
        """Claim the current step's device batch (dispatching now only
        when no prefetch landed); the ``ingest`` hop of a sampled trace
        measures exactly this claim — ~0 when the H2D was hidden."""
        t0 = time.monotonic()
        if self._staged is None:
            batch = self.feed.next_batch(timeout=timeout)
            self._staged = self._dispatch(batch)
            self._c_dispatch_now.inc()
        out, trace = self._staged
        self._staged = None
        self._h_claim.observe(time.monotonic() - t0)
        if trace is not None:
            out = dict(out)
            out["_trace"] = trace.hop("ingest", self.tele_role)
        return out


# --------------------------------------------------------------------------
# the pod learner's shape-keyed block stager
# --------------------------------------------------------------------------


class StagedBlock:
    """One host-staged experience block awaiting its device transfer."""

    __slots__ = ("arrays", "slot_key", "slot_idx", "stager")

    def __init__(self, arrays: Dict[str, np.ndarray], slot_key, slot_idx, stager):
        self.arrays = arrays
        self.slot_key = slot_key
        self.slot_idx = slot_idx
        self.stager = stager


class BlockStager:
    """Reused host staging buffers for wire-fed [T, B] experience blocks.

    Replaces ``pod/learner.py``'s seven fresh ``np.ascontiguousarray``
    allocations per shipped block with ONE staging write into per-shape
    reusable buffers (the wire's frombuffer views are read exactly once),
    plus the same ready fence as :class:`HostStagingRing`. Thread
    contract: :meth:`copy_in` may run on the ingest receive thread (the
    wire→staging write then overlaps the learner's step), ``to_device``/
    ``cancel`` on the learner thread — the internal lock serializes slot
    state, never the copies themselves.
    """

    #: field dtypes of a staged block (pod/wire.py EXPERIENCE_KEYS layout)
    DTYPES = {
        "state": np.uint8,
        "action": np.int32,
        "reward": np.float32,
        "done": np.float32,
        "behavior_log_probs": np.float32,
        "behavior_values": np.float32,
        "bootstrap_state": np.uint8,
    }

    #: bounded slot wait before falling back to a transient allocation —
    #: the fence is an in-flight H2D (milliseconds); anything longer means
    #: the consumer is backed up and copy_in must NOT wedge its caller
    #: (the pod ingest's drop-oldest liveness rides on this)
    MAX_WAIT_S = 0.05

    def __init__(self, slots: int = 2, tele_role: str = "learner"):
        self._n = max(2, int(slots))
        self._lock = threading.Lock()
        # shape key -> list of [buffers dict, handles list|None, busy bool]
        self._rings: Dict[tuple, List[list]] = {}
        self._cursors: Dict[tuple, int] = {}
        self.tele_role = tele_role
        self._c_copies, self._c_blocks = _counters(tele_role)
        tele = telemetry.registry(tele_role)
        self._c_alloc = tele.counter("staging_alloc_total")
        self._c_waits = tele.counter("staging_waits_total")
        self._c_fallback = tele.counter("staging_fallback_total")

    def _slot_for(self, key: tuple, shapes: Dict[str, tuple]) -> tuple:
        deadline = time.monotonic() + self.MAX_WAIT_S
        with self._lock:
            ring = self._rings.get(key)
            if ring is None:
                ring = self._rings[key] = []
                self._cursors[key] = 0
            start = self._cursors[key]
            while True:
                fenced = False  # a non-busy slot whose H2D may resolve
                for off in range(len(ring)):
                    idx = (start + off) % len(ring)
                    bufs, handles, busy = ring[idx]
                    if busy:
                        continue
                    if handles is not None and not all(
                        _ready(h) for h in handles
                    ):
                        fenced = True
                        continue
                    ring[idx][1] = None
                    ring[idx][2] = True
                    self._cursors[key] = (idx + 1) % len(ring)
                    return bufs, idx
                if len(ring) < self._n:
                    bufs = {
                        k: np.zeros(shapes[k], self.DTYPES[k])
                        for k in shapes
                    }
                    ring.append([bufs, None, True])
                    self._c_alloc.inc()
                    return bufs, len(ring) - 1
                if not fenced or time.monotonic() >= deadline:
                    # a transient (non-ring) allocation keeps the caller
                    # live, counted so a starved ring is visible. `not
                    # fenced` short-circuits: every slot is HELD
                    # DOWNSTREAM (unconsumed staged blocks — the
                    # backlogged regime drop-oldest exists for), so no
                    # amount of waiting here frees one; only an in-flight
                    # H2D (fenced) is worth the bounded poll
                    self._c_fallback.inc()
                    return (
                        {k: np.zeros(shapes[k], self.DTYPES[k]) for k in shapes},
                        None,
                    )
                # bounded wait, then re-scan (fence = in-flight H2D)
                self._c_waits.inc()
                self._free_wait()  # ba3cflow: disable=F1 — _free_wait drops self._lock around its sleep (see its body)

    def _free_wait(self) -> None:
        # called with the lock held: drop it for the sleep so to_device/
        # cancel can flip slot state
        self._lock.release()
        try:
            time.sleep(0.001)
        finally:
            self._lock.acquire()

    def copy_in(self, batch: Dict[str, np.ndarray]) -> StagedBlock:
        """The one host copy: wire views → this shape's staging buffers.
        Dtype coercion happens here (the program's input contract), same
        as the legacy ``batch_to_block``."""
        t, b = np.shape(batch["action"])
        shapes = {
            "state": np.shape(batch["state"]),
            "action": (t, b),
            "reward": (t, b),
            "done": (t, b),
            "behavior_log_probs": (t, b),
            "behavior_values": (t, b),
            "bootstrap_state": np.shape(batch["bootstrap_state"]),
        }
        key = (shapes["state"], shapes["bootstrap_state"])
        bufs, idx = self._slot_for(key, shapes)
        for k, dst in bufs.items():
            np.copyto(dst, batch[k], casting="unsafe")
        self._c_copies.inc(1.0)
        self._c_blocks.inc()
        return StagedBlock(bufs, key, idx, self)

    def to_device(self, staged: StagedBlock, block_sharding=None):
        """Staged host block → device TrajBlock (async H2D); the slot's
        fence closes on the transfer handles."""
        import jax

        from distributed_ba3c_tpu.fused.overlap import TrajBlock

        a = staged.arrays
        leaves = TrajBlock(
            states=a["state"],
            actions=a["action"],
            rewards=a["reward"],
            dones=a["done"],
            behavior_log_probs=a["behavior_log_probs"],
            behavior_values=a["behavior_values"],
            bootstrap_state=a["bootstrap_state"],
        )
        if block_sharding is None:
            block = jax.tree_util.tree_map(jax.device_put, leaves)
        else:
            block = jax.tree_util.tree_map(
                jax.device_put, leaves, block_sharding
            )
        dealias = _dealias_fn()
        if dealias is not None:
            block = jax.tree_util.tree_map(dealias, block)
        if staged.slot_idx is not None:
            with self._lock:
                slot = self._rings[staged.slot_key][staged.slot_idx]
                slot[1] = list(jax.tree_util.tree_leaves(block))
                slot[2] = False
        return block

    def cancel(self, staged: StagedBlock) -> None:
        """A gate-rejected block frees its slot without a transfer (no-op
        for transient fallback allocations)."""
        if staged.slot_idx is None:
            return
        with self._lock:
            slot = self._rings[staged.slot_key][staged.slot_idx]
            slot[1] = None
            slot[2] = False
