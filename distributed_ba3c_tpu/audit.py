"""Compiled-program audit plane: entry-point registry + retrace tripwire.

``ba3clint`` (tools/ba3clint) checks what the *source* promises; this module
is the other half — the registry of what the *compiled program* must do.
Each hot-path jit site registers a named entry point with canonical abstract
shapes, and ``tools/ba3caudit`` traces them (``.trace()`` → jaxpr → lowered
HLO → compiled cost analysis) and checks IR-level invariants the north-star
number lives on:

    T1  no f32 compute leaking into the bf16 conv stack
    T2  donation materialized as input→output buffer aliasing
    T3  exactly one gradient all-reduce per step on the data axis
    T4  no host callbacks / debug prints in hot paths
    T5  FLOPs + HBM bytes pinned by the checked-in audit_manifest.json

The registered entry points (one per hot-path jit site):

    parallel.train_step   the sync DP step      (parallel/train_step.py)
    parallel.train_macro_step
                          the multi-fleet macro step: K fleet sub-batches
                          (fleet axis sharded over data), one update
    parallel.vtrace_step  the V-trace step      (parallel/vtrace_step.py)
    parallel.vtrace_macro_step
                          the V-trace macro step (same fleet-major layout)
    fused.step            the fused rollout+update step (fused/loop.py)
    fused.macro_learner   the overlap macro learner: K trajectory blocks
                          accumulated into one update (fused/overlap.py)
    fused.actor           the overlap rollout program (fused/overlap.py) —
                          donation-aliased env carry, collective-free
    fused.learner         the overlap V-trace learner (fused/overlap.py)
    fused.greedy_eval     the on-device greedy Evaluator (fused/loop.py)
    predict.server        the batched action-server forward (predict/server.py)
    predict.server_greedy the greedy (eval/play) server variant — [3, B]
                          packed fetch (the duplicated argmax row dropped)
    predict.server_bf16   the quantized serving forward: bf16 param storage
                          (--rollout_dtype bfloat16), f32 heads — the
                          cheaper program the actor plane serves from,
                          structurally pinned so it cannot silently revert
    fused.actor_bf16      the overlap rollout program at the bf16 params
                          snapshot (fused.prep's cast output) — same pin
    predict.server_int8   the int8 serving forward (--rollout_dtype int8):
                          per-channel symmetric int8 weights + calibrated
                          per-tensor activation scales (quantize/), int8
                          conv accumulate-to-int32, f32 epilogue + heads —
                          the quarter-bandwidth rung, structurally pinned
    fused.actor_int8      the overlap rollout program at the int8 qparams
                          snapshot (fused.prep quantizes on snapshot) —
                          same donation/collective-free contract
    pod.learner           the pod's bounded-staleness V-trace learner
                          (pod/learner.py) — the fused.learner gradient
                          body compiled standalone for host-fed blocks

Canonical shapes are deliberately SMALL (the invariants are shape-class
properties, not magnitude properties) and the canonical mesh is always the
first :data:`CANONICAL_MESH_DEVICES` devices, so the manifest numbers are
identical under the 8-device pytest harness and the standalone CLI.

Runtime tripwire (``BA3C_AUDIT=1``, mirroring ``BA3C_SANITIZE=1``): the same
jit sites route through :func:`tripwire_jit`, which counts trace events per
entry point and raises :class:`AuditError` if a registered program re-traces
after warmup — a silent recompile mid-run is exactly the "bench below 64k
triggers re-investigation" regression (VERDICT.md), now a machine check.
"""

from __future__ import annotations

import dataclasses
import functools
import os
from typing import Any, Callable, Dict, List, Optional, Tuple

AUDIT_ENV = "BA3C_AUDIT"

#: The canonical audit mesh is ALWAYS the first two devices — fixed so the
#: committed manifest does not depend on how many CPU devices the harness
#: happens to force (pytest forces 8; the CLI forces 2).
CANONICAL_MESH_DEVICES = 2


def audit_enabled() -> bool:
    return os.environ.get(AUDIT_ENV, "") not in ("", "0")


class AuditError(RuntimeError):
    """A compiled-program invariant was violated at runtime (tripwire)."""


# --------------------------------------------------------------------------
# runtime retrace tripwire
# --------------------------------------------------------------------------

#: live tripwires by entry-point name (inspection/testing; latest wins)
_LIVE_TRIPWIRES: Dict[str, "RetraceTripwire"] = {}


class RetraceTripwire:
    """Wrap a to-be-jitted function and refuse post-warmup retraces.

    Trace events are counted by instrumenting the *python function itself*
    (its body runs exactly once per cache miss), not a private jit API, so
    the counter is exact on every jax version. By default the tripwire arms
    itself after the first call — the first call IS the warmup compile; any
    later cache miss means an input changed shape/dtype/sharding and the
    entry point silently recompiled. Sites with a legitimate multi-shape
    warmup (the predictor's pow-2 buckets) pass ``auto_arm=False`` and call
    :meth:`arm` when their warmup completes.

    Attribute access falls through to the underlying jitted callable, so
    ``.trace()``/``.lower()`` (the static auditor) keep working.
    """

    def __init__(self, name: str, fn: Callable, jit_kwargs: dict,
                 auto_arm: bool = True):
        import threading

        import jax

        self.name = name
        self.traces = 0
        self.armed = False
        self._auto_arm = auto_arm
        self._lock = threading.Lock()
        # jit traces run synchronously in the CALLING thread, so a
        # thread-local flag attributes each trace to exactly the call that
        # caused it — the predictor shares one tripwire across worker
        # threads, and blaming worker A for worker B's retrace would send
        # the operator debugging the wrong shape
        self._tls = threading.local()

        @functools.wraps(fn)
        def counted(*args, **kwargs):
            with self._lock:
                self.traces += 1
            self._tls.traced = True
            return fn(*args, **kwargs)

        self._jitted = jax.jit(counted, **jit_kwargs)

    def arm(self) -> None:
        """Declare warmup complete: any further trace raises AuditError."""
        self.armed = True

    def __call__(self, *args, **kwargs):
        self._tls.traced = False
        out = self._jitted(*args, **kwargs)
        if self.armed and getattr(self._tls, "traced", False):
            try:
                # leave postmortem evidence before raising: the retrace is
                # exactly the mid-run stall class the flight recorder exists
                # for (telemetry/recorder.py)
                from distributed_ba3c_tpu import telemetry

                telemetry.record(
                    "retrace", entry=self.name, trace=self.traces
                )
                telemetry.dump("AuditError")
            except Exception:
                pass  # telemetry must never mask the audit finding
            raise AuditError(
                f"[audit] entry point {self.name!r} re-traced after warmup "
                f"(trace #{self.traces}) — an input changed "
                "shape/dtype/sharding and the program silently recompiled. "
                "Every recompile stalls the step for the full XLA compile; "
                "fix the unstable input or re-warm explicitly."
            )
        if self._auto_arm and not self.armed and self.traces:
            self.armed = True
        return out

    def __getattr__(self, item):
        return getattr(self._jitted, item)


def tripwire_jit(name: str, fn: Callable, *, auto_arm: bool = True,
                 **jit_kwargs):
    """``jax.jit`` with the BA3C_AUDIT=1 retrace tripwire.

    The single wrapper every registered hot-path jit site uses: a plain
    ``jax.jit(fn, **jit_kwargs)`` when auditing is off (zero overhead), a
    :class:`RetraceTripwire` when ``BA3C_AUDIT=1``.
    """
    import jax

    if not audit_enabled():
        return jax.jit(fn, **jit_kwargs)
    tw = RetraceTripwire(name, fn, jit_kwargs, auto_arm=auto_arm)
    _LIVE_TRIPWIRES[name] = tw
    return tw


def live_tripwires() -> Dict[str, RetraceTripwire]:
    return dict(_LIVE_TRIPWIRES)


# --------------------------------------------------------------------------
# static entry-point registry
# --------------------------------------------------------------------------


@dataclasses.dataclass
class TraceTarget:
    """One registered entry point, built at canonical abstract shapes.

    ``jit_fn`` is the REAL jitted callable from the hot-path module (exposed
    as ``step.audit_jit``), so the auditor sees exactly the program training
    runs — not a re-derivation of it.
    """

    name: str
    jit_fn: Any                      # jitted callable exposing .trace()
    args: Tuple[Any, ...]            # ShapeDtypeStruct pytrees
    #: shapes of the non-scalar param leaves whose gradients must each be
    #: all-reduced EXACTLY once on the data axis; None = entry computes no
    #: gradients (any non-scalar psum is a violation)
    grad_shapes: Optional[List[Tuple[int, ...]]]
    #: flattened input indices of the donated argument's NON-SCALAR leaves:
    #: each must materialize as an input→output alias in the compiled
    #: module (empty = no donation, so no alias may appear at all). Scalar
    #: leaves are excluded — XLA occasionally declines a 4-byte alias (CSE
    #: on identical scalar updates) and nothing rides on it.
    donated_nonscalar_indices: List[int]
    #: False = the program must contain NO collectives at all (predictor)
    allow_collectives: bool = True
    #: required operand dtype for every conv eqn in the program
    conv_dtype: str = "bfloat16"


ENTRY_POINTS: Dict[str, Callable[[], TraceTarget]] = {}


def register_entry(name: str):
    def deco(builder: Callable[[], TraceTarget]):
        ENTRY_POINTS[name] = builder
        return builder

    return deco


def entry_names() -> List[str]:
    return sorted(ENTRY_POINTS)


def build_entry(name: str) -> TraceTarget:
    if name not in ENTRY_POINTS:
        raise KeyError(
            f"unknown audit entry point {name!r}; registered: {entry_names()}"
        )
    return ENTRY_POINTS[name]()


# -- canonical construction helpers ----------------------------------------


def _canonical_parts():
    from distributed_ba3c_tpu.config import BA3CConfig
    from distributed_ba3c_tpu.models.a3c import BA3CNet
    from distributed_ba3c_tpu.ops.gradproc import make_optimizer

    cfg = BA3CConfig(num_actions=6)
    model = BA3CNet(num_actions=cfg.num_actions, fc_units=cfg.fc_units)
    opt = make_optimizer(cfg.learning_rate, cfg.adam_epsilon, cfg.grad_clip_norm)
    return cfg, model, opt


def canonical_mesh():
    import jax

    from distributed_ba3c_tpu.parallel.mesh import make_mesh

    devs = jax.devices()
    if len(devs) < CANONICAL_MESH_DEVICES:
        raise AuditError(
            f"the audit needs {CANONICAL_MESH_DEVICES} devices for its "
            f"canonical mesh, found {len(devs)} — run via "
            "`python -m tools.ba3caudit` (which forces a 2-device CPU "
            "platform) or set --xla_force_host_platform_device_count"
        )
    return make_mesh(
        num_data=CANONICAL_MESH_DEVICES,
        num_model=1,
        devices=devs[:CANONICAL_MESH_DEVICES],
    )


def _key_aval():
    import jax

    return jax.eval_shape(lambda: jax.random.PRNGKey(0))


def _scalar(dtype):
    import jax

    return jax.ShapeDtypeStruct((), dtype)


def _state_avals(model, cfg, opt):
    import jax

    from distributed_ba3c_tpu.parallel.train_step import create_train_state

    return jax.eval_shape(
        lambda k: create_train_state(k, model, cfg, opt), _key_aval()
    )


def _grad_shapes(params_avals) -> List[Tuple[int, ...]]:
    import jax

    return [
        tuple(l.shape)
        for l in jax.tree_util.tree_leaves(params_avals)
        if l.ndim >= 1
    ]


def _donated_indices(state_avals, exempt: Tuple[str, ...] = (),
                     offset: int = 0) -> List[int]:
    """Flattened input indices of the donated arg's non-scalar leaves.

    The donated state is usually positional arg 0, so its leaves occupy the
    first positions of the jit's flattened input list — which is the HLO
    parameter numbering the compiled module's alias table uses. When the
    donated arg comes AFTER others (the overlap actor donates arg 1, its
    env carry, while arg 0 is the params snapshot), ``offset`` is the leaf
    count of the preceding args. ``exempt`` names leaf-path fragments
    excluded from the T2 requirement; every exemption must carry a
    justification comment at the registration site (the manifest's exact
    ``aliased_inputs`` count still pins the total).
    """
    import jax

    out = []
    for i, (path, leaf) in enumerate(
        jax.tree_util.tree_flatten_with_path(state_avals)[0]
    ):
        if leaf.ndim < 1:
            continue
        key = jax.tree_util.keystr(path)
        if any(frag in key for frag in exempt):
            continue
        out.append(offset + i)
    return out


# -- the five entry points --------------------------------------------------


@register_entry("parallel.train_step")
def _build_train_step() -> TraceTarget:
    import jax
    import jax.numpy as jnp

    from distributed_ba3c_tpu.parallel.train_step import make_train_step

    cfg, model, opt = _canonical_parts()
    mesh = canonical_mesh()
    step = make_train_step(model, opt, cfg, mesh)
    state = _state_avals(model, cfg, opt)
    B = 32  # canonical global batch: 16 samples per canonical shard
    batch = {
        "state": jax.ShapeDtypeStruct((B, *cfg.state_shape), jnp.uint8),
        "action": jax.ShapeDtypeStruct((B,), jnp.int32),
        "return": jax.ShapeDtypeStruct((B,), jnp.float32),
    }
    return TraceTarget(
        name="parallel.train_step",
        jit_fn=step.audit_jit,
        args=(state, batch, _scalar(jnp.float32), _scalar(jnp.float32)),
        grad_shapes=_grad_shapes(state.params),
        donated_nonscalar_indices=_donated_indices(state),
    )


@register_entry("parallel.vtrace_step")
def _build_vtrace_step() -> TraceTarget:
    import jax
    import jax.numpy as jnp

    from distributed_ba3c_tpu.parallel.vtrace_step import make_vtrace_train_step

    cfg, model, opt = _canonical_parts()
    mesh = canonical_mesh()
    step = make_vtrace_train_step(model, opt, cfg, mesh)
    state = _state_avals(model, cfg, opt)
    T, B = 4, 8  # canonical unroll: 4 samples per canonical shard
    sds = jax.ShapeDtypeStruct
    batch = {
        "state": sds((T, B, *cfg.state_shape), jnp.uint8),
        "action": sds((T, B), jnp.int32),
        "reward": sds((T, B), jnp.float32),
        "done": sds((T, B), jnp.float32),
        "behavior_log_probs": sds((T, B), jnp.float32),
        "bootstrap_state": sds((B, *cfg.state_shape), jnp.uint8),
    }
    return TraceTarget(
        name="parallel.vtrace_step",
        jit_fn=step.audit_jit,
        args=(state, batch, _scalar(jnp.float32), _scalar(jnp.float32)),
        grad_shapes=_grad_shapes(state.params),
        donated_nonscalar_indices=_donated_indices(state),
    )


@register_entry("parallel.train_macro_step")
def _build_train_macro_step() -> TraceTarget:
    import jax
    import jax.numpy as jnp

    from distributed_ba3c_tpu.parallel.train_step import make_macro_train_step

    cfg, model, opt = _canonical_parts()
    mesh = canonical_mesh()
    # canonical macro shape: K=4 fleets over the 2-device mesh — 2 fleets
    # per shard, so the sequential accumulation scan is IN the program
    # (K == D would compile the scan away and pin the wrong structure)
    K, B = 4, 16
    step = make_macro_train_step(model, opt, cfg, mesh, n_fleets=K)
    state = _state_avals(model, cfg, opt)
    batch = {
        "state": jax.ShapeDtypeStruct((K, B, *cfg.state_shape), jnp.uint8),
        "action": jax.ShapeDtypeStruct((K, B), jnp.int32),
        "return": jax.ShapeDtypeStruct((K, B), jnp.float32),
    }
    return TraceTarget(
        name="parallel.train_macro_step",
        jit_fn=step.audit_jit,
        args=(state, batch, _scalar(jnp.float32), _scalar(jnp.float32)),
        grad_shapes=_grad_shapes(state.params),
        donated_nonscalar_indices=_donated_indices(state),
    )


@register_entry("parallel.vtrace_macro_step")
def _build_vtrace_macro_step() -> TraceTarget:
    import jax
    import jax.numpy as jnp

    from distributed_ba3c_tpu.parallel.vtrace_step import make_vtrace_macro_step

    cfg, model, opt = _canonical_parts()
    mesh = canonical_mesh()
    # K=4 over D=2 for the same in-program-scan reason as the BA3C macro
    K, T, B = 4, 4, 8
    step = make_vtrace_macro_step(model, opt, cfg, mesh, n_fleets=K)
    state = _state_avals(model, cfg, opt)
    sds = jax.ShapeDtypeStruct
    batch = {
        "state": sds((K, T, B, *cfg.state_shape), jnp.uint8),
        "action": sds((K, T, B), jnp.int32),
        "reward": sds((K, T, B), jnp.float32),
        "done": sds((K, T, B), jnp.float32),
        "behavior_log_probs": sds((K, T, B), jnp.float32),
        "bootstrap_state": sds((K, B, *cfg.state_shape), jnp.uint8),
    }
    return TraceTarget(
        name="parallel.vtrace_macro_step",
        jit_fn=step.audit_jit,
        args=(state, batch, _scalar(jnp.float32), _scalar(jnp.float32)),
        grad_shapes=_grad_shapes(state.params),
        donated_nonscalar_indices=_donated_indices(state),
    )


@register_entry("fused.step")
def _build_fused_step() -> TraceTarget:
    import jax
    import jax.numpy as jnp

    from distributed_ba3c_tpu.envs.jaxenv import pong
    from distributed_ba3c_tpu.fused.loop import (
        create_fused_state,
        make_fused_step,
    )

    cfg, model, opt = _canonical_parts()
    mesh = canonical_mesh()
    n_envs = 2 * CANONICAL_MESH_DEVICES  # 2 envs per canonical shard
    step = make_fused_step(model, opt, cfg, mesh, pong, rollout_len=4)
    state = jax.eval_shape(
        lambda k: create_fused_state(
            k, model, cfg, opt, pong, n_envs,
            n_shards=CANONICAL_MESH_DEVICES,
        ),
        _key_aval(),
    )
    return TraceTarget(
        name="fused.step",
        jit_fn=step.audit_jit,
        args=(state, _scalar(jnp.float32), _scalar(jnp.float32)),
        grad_shapes=_grad_shapes(state.train.params),
        # ep_return_sum: XLA's buffer assignment declines this one alias
        # (the new value feeds both the carried state and the episode
        # metrics psum) — [n_envs] f32, a few KB at real scale, nothing
        # rides on it. Pinned by the manifest's aliased_inputs count.
        donated_nonscalar_indices=_donated_indices(
            state, exempt=("ep_return_sum",)
        ),
    )


@register_entry("fused.actor")
def _build_overlap_actor() -> TraceTarget:
    import jax

    from distributed_ba3c_tpu.envs.jaxenv import pong
    from distributed_ba3c_tpu.fused.loop import create_fused_state
    from distributed_ba3c_tpu.fused.overlap import ActorState, make_overlap_step

    cfg, model, opt = _canonical_parts()
    mesh = canonical_mesh()
    n_envs = 2 * CANONICAL_MESH_DEVICES  # 2 envs per canonical shard
    step = make_overlap_step(model, opt, cfg, mesh, pong, rollout_len=4)
    state = jax.eval_shape(
        lambda k: create_fused_state(
            k, model, cfg, opt, pong, n_envs,
            n_shards=CANONICAL_MESH_DEVICES,
        ),
        _key_aval(),
    )
    astate = ActorState(
        env_state=state.env_state,
        obs_stack=state.obs_stack,
        key=state.key,
        ep_return=state.ep_return,
        ep_count=state.ep_count,
        ep_return_sum=state.ep_return_sum,
    )
    params = state.train.params
    return TraceTarget(
        name="fused.actor",
        jit_fn=step.actor_jit,
        # arg 0 is the params SNAPSHOT (fused.prep's output), arg 1 the
        # donated env carry — its leaves sit after every params leaf in
        # the HLO parameter numbering
        args=(params, astate),
        grad_shapes=None,
        donated_nonscalar_indices=_donated_indices(
            astate,
            offset=len(jax.tree_util.tree_leaves(params)),
        ),
        # the overlap schedule's whole premise: the rollout program has
        # nothing to wait on — single-chip form must be collective-free
        allow_collectives=False,
    )


@register_entry("fused.learner")
def _build_overlap_learner() -> TraceTarget:
    import jax
    import jax.numpy as jnp

    from distributed_ba3c_tpu.envs.jaxenv import pong
    from distributed_ba3c_tpu.fused.overlap import TrajBlock, make_overlap_step

    cfg, model, opt = _canonical_parts()
    mesh = canonical_mesh()
    step = make_overlap_step(model, opt, cfg, mesh, pong, rollout_len=4)
    train = _state_avals(model, cfg, opt)
    T, B = 4, 2 * CANONICAL_MESH_DEVICES  # one canonical actor block
    sds = jax.ShapeDtypeStruct
    block = TrajBlock(
        states=sds((T, B, *cfg.state_shape), jnp.uint8),
        actions=sds((T, B), jnp.int32),
        rewards=sds((T, B), jnp.float32),
        dones=sds((T, B), jnp.float32),
        behavior_log_probs=sds((T, B), jnp.float32),
        behavior_values=sds((T, B), jnp.float32),
        bootstrap_state=sds((B, *cfg.state_shape), jnp.uint8),
    )
    return TraceTarget(
        name="fused.learner",
        jit_fn=step.learner_jit,
        args=(train, block, _scalar(jnp.float32), _scalar(jnp.float32)),
        grad_shapes=_grad_shapes(train.params),
        # only the train state is donated — the block must stay live (it
        # is the double-buffer slot the actor wrote; no learner output
        # matches its shapes, so an alias is impossible anyway)
        donated_nonscalar_indices=_donated_indices(train),
    )


@register_entry("fused.macro_learner")
def _build_overlap_macro_learner() -> TraceTarget:
    import jax
    import jax.numpy as jnp

    from distributed_ba3c_tpu.envs.jaxenv import pong
    from distributed_ba3c_tpu.fused.overlap import TrajBlock, make_overlap_step

    cfg, model, opt = _canonical_parts()
    mesh = canonical_mesh()
    K = 2  # canonical macro window count (the accumulation scan is per-shard)
    step = make_overlap_step(
        model, opt, cfg, mesh, pong, rollout_len=4, macro_fleets=K
    )
    train = _state_avals(model, cfg, opt)
    T, B = 4, 2 * CANONICAL_MESH_DEVICES  # one canonical actor block each
    sds = jax.ShapeDtypeStruct
    block = TrajBlock(
        states=sds((T, B, *cfg.state_shape), jnp.uint8),
        actions=sds((T, B), jnp.int32),
        rewards=sds((T, B), jnp.float32),
        dones=sds((T, B), jnp.float32),
        behavior_log_probs=sds((T, B), jnp.float32),
        behavior_values=sds((T, B), jnp.float32),
        bootstrap_state=sds((B, *cfg.state_shape), jnp.uint8),
    )
    return TraceTarget(
        name="fused.macro_learner",
        jit_fn=step.macro_learner_jit,
        args=(train, (block,) * K, _scalar(jnp.float32), _scalar(jnp.float32)),
        grad_shapes=_grad_shapes(train.params),
        # only the train state is donated — the K blocks are the actor's
        # double-buffer slots, same non-donation contract as fused.learner
        donated_nonscalar_indices=_donated_indices(train),
    )


@register_entry("fused.greedy_eval")
def _build_greedy_eval() -> TraceTarget:
    import jax
    import jax.numpy as jnp

    from distributed_ba3c_tpu.envs.jaxenv import pong
    from distributed_ba3c_tpu.fused.loop import make_greedy_eval

    cfg, model, opt = _canonical_parts()
    mesh = canonical_mesh()
    evaluate = make_greedy_eval(
        model, cfg, mesh, pong, n_envs=CANONICAL_MESH_DEVICES, max_steps=8
    )
    params = _state_avals(model, cfg, opt).params
    return TraceTarget(
        name="fused.greedy_eval",
        jit_fn=evaluate.audit_jit,
        args=(params, _scalar(jnp.uint32)),
        grad_shapes=None,  # pure inference: a param-shaped psum is a bug
        donated_nonscalar_indices=[],
    )


@register_entry("predict.server")
def _build_predict_server() -> TraceTarget:
    import jax
    import jax.numpy as jnp

    from distributed_ba3c_tpu.predict.server import make_fwd_sample

    cfg, model, opt = _canonical_parts()
    params = _state_avals(model, cfg, opt).params
    B = 16  # canonical serving bucket (cfg.predict_batch_size)
    states = jax.ShapeDtypeStruct((B, *cfg.state_shape), jnp.uint8)
    return TraceTarget(
        name="predict.server",
        jit_fn=jax.jit(make_fwd_sample(model, greedy=False)),
        args=(params, states, _key_aval()),
        grad_shapes=None,
        donated_nonscalar_indices=[],
        # single-device serving path: any collective here means a mesh
        # sharding leaked into the action server
        allow_collectives=False,
    )


def _bf16_params(params_avals):
    """f32 param leaves → bf16 avals (what fused.prep's cast / the
    predictor's publish-cast hands the rollout-side programs)."""
    import jax
    import jax.numpy as jnp

    return jax.tree_util.tree_map(
        lambda l: jax.ShapeDtypeStruct(l.shape, jnp.bfloat16)
        if l.dtype == jnp.float32 else l,
        params_avals,
    )


@register_entry("predict.server_bf16")
def _build_predict_server_bf16() -> TraceTarget:
    import jax
    import jax.numpy as jnp

    from distributed_ba3c_tpu.predict.server import make_fwd_sample

    cfg, model, opt = _canonical_parts()
    params = _bf16_params(_state_avals(model, cfg, opt).params)
    B = 16  # same canonical bucket as predict.server
    states = jax.ShapeDtypeStruct((B, *cfg.state_shape), jnp.uint8)
    return TraceTarget(
        # the quantized serving/actor forward (--rollout_dtype bfloat16):
        # same fwd_sample body, bf16 param STORAGE — a distinct compiled
        # program whose halved param reads T5 pins separately (the f32
        # entry must not silently absorb the cheap program's cost profile,
        # nor vice versa); T1 still requires the bf16 conv stack and the
        # log-prob heads stay f32 (models/a3c.py)
        name="predict.server_bf16",
        jit_fn=jax.jit(make_fwd_sample(model, greedy=False)),
        args=(params, states, _key_aval()),
        grad_shapes=None,
        donated_nonscalar_indices=[],
        allow_collectives=False,
    )


@register_entry("fused.actor_bf16")
def _build_overlap_actor_bf16() -> TraceTarget:
    import jax

    from distributed_ba3c_tpu.envs.jaxenv import pong
    from distributed_ba3c_tpu.fused.loop import create_fused_state
    from distributed_ba3c_tpu.fused.overlap import ActorState, make_overlap_step

    cfg, model, opt = _canonical_parts()
    mesh = canonical_mesh()
    n_envs = 2 * CANONICAL_MESH_DEVICES  # 2 envs per canonical shard
    step = make_overlap_step(
        model, opt, cfg, mesh, pong, rollout_len=4,
        rollout_dtype="bfloat16",
    )
    state = jax.eval_shape(
        lambda k: create_fused_state(
            k, model, cfg, opt, pong, n_envs,
            n_shards=CANONICAL_MESH_DEVICES,
        ),
        _key_aval(),
    )
    astate = ActorState(
        env_state=state.env_state,
        obs_stack=state.obs_stack,
        key=state.key,
        ep_return=state.ep_return,
        ep_count=state.ep_count,
        ep_return_sum=state.ep_return_sum,
    )
    params = _bf16_params(state.train.params)
    return TraceTarget(
        # the overlap rollout at the bf16 snapshot (fused.prep's cast):
        # same donation-aliased env carry and collective-free contract as
        # fused.actor, traced at the bf16 param avals the bf16 schedule
        # actually feeds it — its halved param-read bytes get their own
        # T5 row instead of hiding behind the f32 entry
        name="fused.actor_bf16",
        jit_fn=step.actor_jit,
        args=(params, astate),
        grad_shapes=None,
        donated_nonscalar_indices=_donated_indices(
            astate,
            offset=len(jax.tree_util.tree_leaves(params)),
        ),
        allow_collectives=False,
    )


def _int8_qparams(model, params_avals):
    """f32 param avals → quantized-table avals (what the predictor's
    publish-quantize / fused.prep's snapshot-quantize hands the int8
    programs). The spec's SCALE VALUES never shape the program — one
    compiled forward per shape class serves every calibration — so a
    placeholder all-1.0 spec yields the exact avals the live table has."""
    import jax

    from distributed_ba3c_tpu.quantize import QuantSpec, quant_layer_names, quantize_params

    spec = QuantSpec(
        act_scales={n: 1.0 for n in quant_layer_names(model)}
    )
    return jax.eval_shape(lambda p: quantize_params(p, spec), params_avals)


@register_entry("predict.server_int8")
def _build_predict_server_int8() -> TraceTarget:
    import jax
    import jax.numpy as jnp

    from distributed_ba3c_tpu.quantize import make_quant_fwd_sample

    cfg, model, opt = _canonical_parts()
    qparams = _int8_qparams(model, _state_avals(model, cfg, opt).params)
    B = 16  # same canonical bucket as predict.server
    states = jax.ShapeDtypeStruct((B, *cfg.state_shape), jnp.uint8)
    return TraceTarget(
        # the int8 serving forward (--rollout_dtype int8): same packed-fetch
        # contract as predict.server, int8 param STORAGE with per-channel
        # weight scales riding in the table — T1 here requires every conv
        # to run int8×int8 (accumulate-to-int32 via preferred_element_type;
        # a dequantize-first regression shows up as f32 operands), and T5
        # pins the quartered param reads on their own row
        name="predict.server_int8",
        jit_fn=jax.jit(make_quant_fwd_sample(model, greedy=False)),
        args=(qparams, states, _key_aval()),
        grad_shapes=None,
        donated_nonscalar_indices=[],
        allow_collectives=False,
        conv_dtype="int8",
    )


@register_entry("fused.actor_int8")
def _build_overlap_actor_int8() -> TraceTarget:
    import jax

    from distributed_ba3c_tpu.envs.jaxenv import pong
    from distributed_ba3c_tpu.fused.loop import create_fused_state
    from distributed_ba3c_tpu.fused.overlap import ActorState, make_overlap_step
    from distributed_ba3c_tpu.quantize import QuantSpec, quant_layer_names

    cfg, model, opt = _canonical_parts()
    mesh = canonical_mesh()
    n_envs = 2 * CANONICAL_MESH_DEVICES  # 2 envs per canonical shard
    spec = QuantSpec(
        act_scales={n: 1.0 for n in quant_layer_names(model)}
    )
    step = make_overlap_step(
        model, opt, cfg, mesh, pong, rollout_len=4,
        rollout_dtype="int8", quant_spec=spec,
    )
    state = jax.eval_shape(
        lambda k: create_fused_state(
            k, model, cfg, opt, pong, n_envs,
            n_shards=CANONICAL_MESH_DEVICES,
        ),
        _key_aval(),
    )
    astate = ActorState(
        env_state=state.env_state,
        obs_stack=state.obs_stack,
        key=state.key,
        ep_return=state.ep_return,
        ep_count=state.ep_count,
        ep_return_sum=state.ep_return_sum,
    )
    qparams = _int8_qparams(model, state.train.params)
    return TraceTarget(
        # the overlap rollout at the int8 qparams snapshot (fused.prep
        # quantizes on snapshot): same donation-aliased env carry and
        # collective-free contract as fused.actor/_bf16, traced at the
        # quantized-table avals the int8 schedule actually feeds it
        name="fused.actor_int8",
        jit_fn=step.actor_jit,
        args=(qparams, astate),
        grad_shapes=None,
        donated_nonscalar_indices=_donated_indices(
            astate,
            offset=len(jax.tree_util.tree_leaves(qparams)),
        ),
        allow_collectives=False,
        conv_dtype="int8",
    )


@register_entry("pod.learner")
def _build_pod_learner() -> TraceTarget:
    import jax
    import jax.numpy as jnp

    from distributed_ba3c_tpu.fused.overlap import TrajBlock
    from distributed_ba3c_tpu.pod.learner import make_pod_learner_step

    cfg, model, opt = _canonical_parts()
    mesh = canonical_mesh()
    step = make_pod_learner_step(model, opt, cfg, mesh)
    train = _state_avals(model, cfg, opt)
    T, B = 4, 2 * CANONICAL_MESH_DEVICES  # one canonical host-fed block
    sds = jax.ShapeDtypeStruct
    block = TrajBlock(
        states=sds((T, B, *cfg.state_shape), jnp.uint8),
        actions=sds((T, B), jnp.int32),
        rewards=sds((T, B), jnp.float32),
        dones=sds((T, B), jnp.float32),
        behavior_log_probs=sds((T, B), jnp.float32),
        behavior_values=sds((T, B), jnp.float32),
        bootstrap_state=sds((B, *cfg.state_shape), jnp.uint8),
    )
    return TraceTarget(
        name="pod.learner",
        jit_fn=step.audit_jit,
        args=(train, block, _scalar(jnp.float32), _scalar(jnp.float32)),
        grad_shapes=_grad_shapes(train.params),
        # same donation contract as fused.learner: only the train state —
        # the block stays live for the LaggedBlockDriver's double buffer
        donated_nonscalar_indices=_donated_indices(train),
    )


@register_entry("predict.server_greedy")
def _build_predict_server_greedy() -> TraceTarget:
    import jax
    import jax.numpy as jnp

    from distributed_ba3c_tpu.predict.server import make_fwd_sample

    cfg, model, opt = _canonical_parts()
    params = _state_avals(model, cfg, opt).params
    B = 16  # same canonical bucket as predict.server
    states = jax.ShapeDtypeStruct((B, *cfg.state_shape), jnp.uint8)
    return TraceTarget(
        name="predict.server_greedy",
        # the eval/play servers' program: greedy=True drops the duplicated
        # argmax row, shrinking the packed fetch to [3, B] — registering
        # BOTH shapes keeps T5 pinned on each (the sampling entry must not
        # silently absorb the greedy server's cost profile)
        jit_fn=jax.jit(make_fwd_sample(model, greedy=True)),
        args=(params, states, _key_aval()),
        grad_shapes=None,
        donated_nonscalar_indices=[],
        allow_collectives=False,
    )
