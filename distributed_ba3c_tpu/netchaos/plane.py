"""NetChaosPlane: schedule-driven interposition on the fleet/pod port map.

One plane owns one :class:`FaultSchedule` and a set of proxy pumps
(netchaos/proxy.py) standing between real endpoints and the processes
that would have connected to them. Addressing is the whole trick
(docs/netchaos.md): the repo's transports derive every channel from a
base pipe pair — ``fleet_pipes`` for the actor plane, ``pod_endpoints``
(+100..+102) for the pod — so handing a process a *proxied base pair*
re-routes every derived channel through the injector with ZERO changes
to the process under test. :meth:`wrap_pod` and :meth:`wrap_fleet` are
exactly that derivation, proxied.

Every injected event lands three ways:

- the plane's own bounded event log — ``(t_rel, link, dir, seq, kind)``
  — the replay source of truth the bench artifacts embed;
- ``netchaos_<kind>_total`` counters on the ``netchaos`` registry (the
  scrape endpoint shows injection live);
- the flight recorder (kind ``netchaos_inject``, stamped with the
  schedule seed), so a postmortem dump of a failing rep names the exact
  faults in flight around the failure.

:meth:`replay_check` is the determinism gate: it re-derives, from the
seed alone, the discrete-fault decision for every message sequence the
run carried and diffs it against the recorded log — byte-for-byte equal
or the rep is not replayable and the bench fails.
"""

from __future__ import annotations

import socket as _socket
import threading
import time
from typing import Dict, List, Optional, Tuple

import zmq

from distributed_ba3c_tpu import telemetry
from distributed_ba3c_tpu.netchaos.proxy import (
    LinkProxy,
    PubProxy,
    PushPullProxy,
    RouterProxy,
)
from distributed_ba3c_tpu.netchaos.schedule import (
    RNG_KINDS,
    FaultSchedule,
)
from distributed_ba3c_tpu.pod.wire import POD_PORT_OFFSET, pod_endpoints
from distributed_ba3c_tpu.utils import logger
from distributed_ba3c_tpu.utils.serialize import loads

#: event kinds that are NOT replayable from the RNG alone: partition
#: window entry/exit is time-driven (seq -1, the link simply stops
#: draining) and overflow is the receiving socket's backpressure, not
#: the schedule's decision
MASK_KINDS = ("partition_start", "partition_heal", "overflow")


def _sniff_ident(frames: List[bytes]) -> Optional[bytes]:
    """Best-effort sender-ident extraction from a c2s message (both wire
    layouts put it first: per-env ``[ident, ...]`` payloads, block header
    ``meta[0]``). Junk in, None out — the sniffer must never kill a pump."""
    try:
        decoded = loads(frames[0])
        ident = decoded[0][0] if len(frames) > 1 else decoded[0]
        if isinstance(ident, (bytes, bytearray, memoryview)):
            return bytes(ident)
        return str(ident).encode()
    # ba3cwire: disable=W4 — the sniffer classifies, never drops: an undecodable message still flows through the pump unfiltered, so there is no reject to count
    except Exception:
        return None


def _tcp_parts(addr: str) -> Optional[Tuple[str, int]]:
    if not addr.startswith("tcp://"):
        return None
    host, _, port = addr[len("tcp://"):].rpartition(":")
    return host, int(port)


def _port_block_free(host: str, ports: List[int]) -> bool:
    for p in ports:
        s = _socket.socket()
        try:
            s.bind((host if host not in ("*",) else "127.0.0.1", p))
        except OSError:
            return False
        finally:
            s.close()
    return True


def _alloc_base(host: str, offsets: List[int], tries: int = 16) -> int:
    """A base port such that base+offset is free for every offset."""
    for _ in range(tries):
        s = _socket.socket()
        s.bind((host if host != "*" else "127.0.0.1", 0))
        base = s.getsockname()[1]
        s.close()
        if _port_block_free(host, [base + o for o in offsets if o != 0]):
            return base
    raise RuntimeError(
        f"could not find a free port block for offsets {offsets}"
    )


class NetChaosPlane:
    """Owns the proxies, the event log, and the replay contract."""

    def __init__(
        self,
        schedule: FaultSchedule,
        max_events: int = 200_000,
        push_pull_front_hwm: int = 64,
        arm_on_start: bool = True,
    ):
        """``arm_on_start=False`` keeps TIMED faults (partition windows)
        dormant until :meth:`rebase_clock` — a rig whose warmup length is
        unknowable (per-host jax imports) must not have the window fire a
        first time mid-boot and then replay after the rebase. Per-message
        faults (seq-keyed) are always live."""
        if isinstance(schedule, str):
            schedule = FaultSchedule.from_json(schedule)
        elif isinstance(schedule, dict):
            schedule = FaultSchedule(
                schedule.get("links", {}), seed=schedule.get("seed", 0)  # ba3cflow: disable=F6 — isinstance(schedule, dict) branch: the param is a plain dict here, not a FaultSchedule
            )
        self.schedule: FaultSchedule = schedule
        self.push_pull_front_hwm = int(push_pull_front_hwm)
        self.context = zmq.Context()
        self.proxies: List[LinkProxy] = []
        self._events: List[tuple] = []
        self._events_dropped = 0
        self._max_events = int(max_events)
        self._lock = threading.Lock()
        self._t0 = time.monotonic()
        self._armed = bool(arm_on_start)
        self._started = False
        tele = telemetry.registry("netchaos")
        self._counters = {
            k: tele.counter(f"netchaos_{k}_total")
            for k in RNG_KINDS + MASK_KINDS
        }

    # -- time + event accounting -------------------------------------------
    def t_rel(self) -> float:
        if not self._armed:
            # dormant clock: no partition window covers a negative time,
            # so timed faults stay off until the rebase arms them
            return -1.0
        return time.monotonic() - self._t0

    def rebase_clock(self) -> None:
        """(Arm and) re-zero the schedule clock. Partition windows are
        relative to it; a bench whose warmup length is unknowable
        (per-host jax imports) rebases right before its measurement
        window so a ``[2s, 6s)`` partition means exactly that."""
        self._armed = True
        self._t0 = time.monotonic()

    def event(self, link: str, direction: str, seq: int, kind: str) -> None:
        t = round(self.t_rel(), 4)
        with self._lock:
            if len(self._events) < self._max_events:
                self._events.append((t, link, direction, seq, kind))
            else:
                self._events_dropped += 1
        c = self._counters.get(kind)
        if c is not None:
            c.inc()
        telemetry.record(
            "netchaos_inject",
            link=link, dir=direction, seq=seq, fault=kind,
            seed=self.schedule.seed, t_rel=t,
        )

    def events(self) -> List[dict]:
        with self._lock:
            evs = list(self._events)
        return [
            {"t": t, "link": l, "dir": d, "seq": s, "kind": k}
            for t, l, d, s, k in evs
        ]

    def summary(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for e in self.events():
            out[e["kind"]] = out.get(e["kind"], 0) + 1
        if self._events_dropped:
            out["log_dropped"] = self._events_dropped
        return out

    # -- proxy construction -------------------------------------------------
    def _front_for(self, back_addr: str, suffix: str) -> str:
        parts = _tcp_parts(back_addr)
        if parts is None:
            return f"{back_addr}-nc{suffix}"
        host, _ = parts
        base = _alloc_base(host, [0])
        return f"tcp://{host}:{base}"

    def add_push_pull(
        self, link: str, back_addr: str, front_addr: Optional[str] = None,
        on_message=None,
    ) -> str:
        front_addr = front_addr or self._front_for(back_addr, f"-{link}")
        self.proxies.append(
            PushPullProxy(
                link, self.schedule, self, front_addr, back_addr,
                self.context, on_message=on_message,
                front_hwm=self.push_pull_front_hwm,
            )
        )
        return front_addr

    def add_pub(
        self, link: str, back_addr: str, front_addr: Optional[str] = None
    ) -> str:
        front_addr = front_addr or self._front_for(back_addr, f"-{link}")
        self.proxies.append(
            PubProxy(
                link, self.schedule, self, front_addr, back_addr, self.context
            )
        )
        return front_addr

    def add_router(
        self, link: str, back_addr: str, front_addr: Optional[str] = None
    ) -> RouterProxy:
        front_addr = front_addr or self._front_for(back_addr, f"-{link}")
        proxy = RouterProxy(
            link, self.schedule, self, front_addr, back_addr, self.context
        )
        self.proxies.append(proxy)
        return proxy

    # -- port-map wrapping (THE addressing trick) ---------------------------
    def wrap_pod(self, pipe_c2s: str, pipe_s2c: str) -> Tuple[str, str]:
        """Proxy every pod channel of a learner at ``(pipe_c2s, pipe_s2c)``.

        Returns a *front base pair*: hand it to actor hosts as their
        ``--learner_c2s/--learner_s2c`` and their own ``pod_endpoints``
        derivation (+100..+102) lands exactly on the proxy fronts —
        ``params_pub``, ``params_fetch`` and ``experience`` each become a
        schedulable link, the host process unchanged."""
        real = pod_endpoints(pipe_c2s, pipe_s2c)
        parts = _tcp_parts(pipe_c2s)
        if parts is not None:
            host, _ = parts
            off = POD_PORT_OFFSET
            base = _alloc_base(host, [off, off + 1, off + 2])
            front_c2s = f"tcp://{host}:{base}"
            front_s2c = f"tcp://{host}:{base + 1}"
        else:
            front_c2s = f"{pipe_c2s}-nc"
            front_s2c = f"{pipe_s2c}-nc"
        fronts = pod_endpoints(front_c2s, front_s2c)
        self.add_pub("params_pub", real.params_pub, fronts.params_pub)
        self.add_router("params_fetch", real.params_fetch, fronts.params_fetch)
        self.add_push_pull("experience", real.experience, fronts.experience)
        logger.info(
            "netchaos wraps pod: %s -> %s (seed %d)",
            front_c2s, pipe_c2s, self.schedule.seed,
        )
        return front_c2s, front_s2c

    def wrap_fleet(self, pipe_c2s: str, pipe_s2c: str) -> Tuple[str, str]:
        """Proxy a master's experience/action pipe pair: env servers get
        the returned front pair; ``c2s`` and ``s2c`` become schedulable
        links. The s2c ROUTER proxy learns client identities from the c2s
        traffic (clients never speak on s2c), so ident-routed action
        replies keep routing through the interposition."""
        parts = _tcp_parts(pipe_c2s)
        if parts is not None:
            host, _ = parts
            base = _alloc_base(host, [0, 1])
            front_c2s = f"tcp://{host}:{base}"
            front_s2c = f"tcp://{host}:{base + 1}"
        else:
            front_c2s = f"{pipe_c2s}-nc"
            front_s2c = f"{pipe_s2c}-nc"
        s2c_proxy = self.add_router("s2c", pipe_s2c, front_s2c)

        def sniff(frames: List[bytes]) -> None:
            ident = _sniff_ident(frames)
            if ident is not None:
                s2c_proxy.ensure_ident(ident)

        self.add_push_pull("c2s", pipe_c2s, front_c2s, on_message=sniff)
        logger.info(
            "netchaos wraps fleet: %s -> %s (seed %d)",
            front_c2s, pipe_c2s, self.schedule.seed,
        )
        return front_c2s, front_s2c

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        self._t0 = time.monotonic()
        for p in self.proxies:  # ba3clint: disable=A15 — idempotent launch guard: each proxy starts at most once, nothing is respawned
            if not p.is_alive():
                p.start()
        self._started = True

    def stop(self) -> None:
        for p in self.proxies:
            p.stop()

    def close(self) -> None:
        for p in self.proxies:
            p.close()
        try:
            self.context.destroy(linger=0)
        except zmq.ZMQError:
            pass

    # -- the determinism gate -----------------------------------------------
    def replay_check(self, max_mismatches: int = 8) -> dict:
        """Re-derive every discrete-fault decision from the seed and diff
        against the recorded log.

        For every (link, direction) the run carried messages on, every
        sequence number is re-decided: RNG-kind events (drop/corrupt/
        truncate/reorder) must match exactly; sequences with no recorded
        event must re-decide to no fault; ``partition_drop``/``overflow``
        are time/backpressure-masked and exempt. One mismatch means the
        rep is NOT replayable from its seed — the gate fails."""
        recorded: Dict[Tuple[str, str], Dict[int, str]] = {}
        max_seq: Dict[Tuple[str, str], int] = {}
        for e in self.events():
            if e["seq"] < 0:
                continue  # time-masked transitions (partition windows)
            key = (e["link"], e["dir"])
            recorded.setdefault(key, {})[e["seq"]] = e["kind"]
            max_seq[key] = max(max_seq.get(key, -1), e["seq"])
        for p in self.proxies:
            for d, n in p._seq.items():
                if n:
                    key = (p.link, d)
                    max_seq[key] = max(max_seq.get(key, -1), n - 1)
        mismatches: List[dict] = []
        checked = 0
        for key, top in max_seq.items():
            link, direction = key
            seen = recorded.get(key, {})
            for seq in range(top + 1):
                got = seen.get(seq)
                if got in MASK_KINDS:
                    continue
                want = self.schedule.decide(link, direction, seq).kind
                checked += 1
                if got != want:
                    if len(mismatches) < max_mismatches:
                        mismatches.append({
                            "link": link, "dir": direction, "seq": seq,
                            "recorded": got, "replayed": want,
                        })
        return {
            "seed": self.schedule.seed,
            "checked": checked,
            "events": len(self._events),
            "events_dropped": self._events_dropped,
            "match": not mismatches and not self._events_dropped,
            "mismatches": mismatches,
        }
