"""Seeded, deterministic per-link fault schedules (docs/netchaos.md).

A :class:`FaultSchedule` names every fault the injection plane may apply
to a link, per direction, per message:

- **latency** (fixed + uniform jitter) and **bandwidth caps** — timing
  faults, applied by the proxy's delay queue;
- **drop / corrupt / truncate / reorder** — discrete per-message faults,
  decided by :meth:`FaultSchedule.decide`;
- **partitions** — timed windows (full or asymmetric by direction)
  relative to the plane's start, during which a direction delivers
  nothing.

The determinism contract is the whole point: ``decide(link, direction,
seq)`` is a PURE function of ``(schedule seed, link name, direction,
message sequence number)`` — a counter-based RNG, not a shared stream —
so a failing rep replays exactly: re-running the same schedule against
the same message sequence re-injects the same faults, and
:meth:`NetChaosPlane.replay_check <distributed_ba3c_tpu.netchaos.plane.
NetChaosPlane.replay_check>` can re-derive a finished run's entire event
log from the seed alone and diff it against what was flight-recorded.

Discrete faults are mutually exclusive by precedence (drop > corrupt >
truncate > reorder) so each message carries at most one event and the
replayed log is unambiguous. JSON round-trips losslessly
(:meth:`to_json` / :meth:`from_json`); the committed bench artifacts
embed the schedule so the rep is reproducible from the artifact alone.
"""

from __future__ import annotations

import binascii
import dataclasses
import json
from typing import Any, Dict, Mapping, Optional, Tuple

import numpy as np

#: message directions through a proxy: ``fwd`` flows toward the bound
#: (server) side — env steps on c2s, fetch/heartbeats on params_fetch,
#: shipped blocks on experience; ``rev`` flows back toward the clients —
#: action replies, fetch replies, params broadcasts
DIRECTIONS = ("fwd", "rev")

#: discrete per-message fault kinds, in decision precedence order
RNG_KINDS = ("drop", "corrupt", "truncate", "reorder")


@dataclasses.dataclass(frozen=True)
class Partition:
    """One timed partition window, in seconds since plane start.

    ``direction``: ``both`` (full partition), or ``fwd``/``rev`` for an
    asymmetric one (e.g. the learner's broadcasts die while the hosts'
    fetches still arrive — the exact case the cache's side-channel
    self-heal exists for)."""

    start_s: float
    end_s: float
    direction: str = "both"

    def __post_init__(self):
        if not 0 <= self.start_s < self.end_s:
            raise ValueError(
                f"partition window must satisfy 0 <= start < end, got "
                f"[{self.start_s}, {self.end_s})"
            )
        if self.direction not in ("both",) + DIRECTIONS:
            raise ValueError(f"unknown partition direction {self.direction!r}")

    def covers(self, direction: str, t_rel: float) -> bool:
        if self.direction != "both" and self.direction != direction:
            return False
        return self.start_s <= t_rel < self.end_s


@dataclasses.dataclass(frozen=True)
class LinkFaults:
    """Everything the injector may do to one link (both directions)."""

    latency_ms: float = 0.0
    jitter_ms: float = 0.0
    drop: float = 0.0
    corrupt: float = 0.0
    truncate: float = 0.0
    reorder: float = 0.0
    #: extra delay a reordered message takes, so it lands behind its
    #: successors (0 = latency_ms + jitter_ms + 5 ms, a sane default)
    reorder_extra_ms: float = 0.0
    bandwidth_kbps: float = 0.0  # 0 = uncapped
    partitions: Tuple[Partition, ...] = ()

    def __post_init__(self):
        for name in ("drop", "corrupt", "truncate", "reorder"):
            p = getattr(self, name)
            if not 0.0 <= p <= 1.0:
                raise ValueError(f"{name} must be a probability, got {p}")
        for name in (
            "latency_ms", "jitter_ms", "reorder_extra_ms", "bandwidth_kbps"
        ):
            if getattr(self, name) < 0:
                raise ValueError(f"{name} must be >= 0")
        object.__setattr__(
            self, "partitions", tuple(
                p if isinstance(p, Partition) else Partition(**p)
                for p in self.partitions
            ),
        )

    def partitioned(self, direction: str, t_rel: float) -> bool:
        return any(p.covers(direction, t_rel) for p in self.partitions)

    def quiet(self) -> bool:
        """True when this spec injects nothing (the clean control arm)."""
        return self == LinkFaults()


@dataclasses.dataclass(frozen=True)
class Decision:
    """The discrete + stochastic draws for ONE message — pure, replayable."""

    drop: bool = False
    corrupt: bool = False
    truncate: bool = False
    reorder: bool = False
    #: uniform [0,1) draws fixed per message: jitter fraction and the
    #: byte-offset fraction a corrupt/truncate applies at
    jitter_u: float = 0.0
    offset_u: float = 0.0

    @property
    def kind(self) -> Optional[str]:
        for k in RNG_KINDS:
            if getattr(self, k):
                return k
        return None


class FaultSchedule:
    """Per-link fault specs under one seed; ``"*"`` is the default link."""

    def __init__(self, links: Mapping[str, LinkFaults], seed: int = 0):
        self.links: Dict[str, LinkFaults] = {}
        for name, spec in links.items():
            if isinstance(spec, Mapping):
                spec = LinkFaults(**spec)
            if not isinstance(spec, LinkFaults):
                raise TypeError(f"link {name!r}: expected LinkFaults/dict")
            self.links[str(name)] = spec
        self.seed = int(seed)
        self._none = LinkFaults()

    def faults_for(self, link: str) -> LinkFaults:
        return self.links.get(link) or self.links.get("*") or self._none

    # -- the pure decision function (THE replay contract) ------------------
    def decide(self, link: str, direction: str, seq: int) -> Decision:
        f = self.faults_for(link)
        if not (f.drop or f.corrupt or f.truncate or f.reorder or f.jitter_ms):
            return Decision()  # nothing stochastic: skip the RNG entirely
        # counter-based: a fresh generator keyed by (seed, link, dir, seq)
        # — no shared stream, so the decision for message N never depends
        # on how many messages other links (or earlier reps) carried
        key = (
            self.seed & 0xFFFFFFFF,
            binascii.crc32(link.encode()) & 0xFFFFFFFF,
            DIRECTIONS.index(direction),
            int(seq) & 0xFFFFFFFF,
        )
        u = np.random.default_rng(key).random(6)
        drop = bool(u[0] < f.drop)
        corrupt = bool(not drop and u[1] < f.corrupt)
        truncate = bool(not (drop or corrupt) and u[2] < f.truncate)
        reorder = bool(not (drop or corrupt or truncate) and u[3] < f.reorder)
        return Decision(
            drop=drop, corrupt=corrupt, truncate=truncate, reorder=reorder,
            jitter_u=float(u[4]), offset_u=float(u[5]),
        )

    def partitioned(self, link: str, direction: str, t_rel: float) -> bool:
        return self.faults_for(link).partitioned(direction, t_rel)

    # -- JSON round-trip ----------------------------------------------------
    def to_json(self) -> str:
        doc: Dict[str, Any] = {"seed": self.seed, "links": {}}
        for name, f in self.links.items():
            d = dataclasses.asdict(f)
            d["partitions"] = [dataclasses.asdict(p) for p in f.partitions]
            doc["links"][name] = d
        return json.dumps(doc, sort_keys=True)

    @classmethod
    def from_json(cls, text: str) -> "FaultSchedule":
        doc = json.loads(text)
        if not isinstance(doc, dict) or "links" not in doc:
            raise ValueError("schedule JSON needs a top-level 'links' map")
        unknown = set(doc) - {"seed", "links"}
        if unknown:
            # a typoed field must fail loudly, not silently inject nothing
            # (the FleetSpec unknown-field lesson)
            raise ValueError(f"unknown schedule fields: {sorted(unknown)}")
        return cls(doc["links"], seed=doc.get("seed", 0))

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, FaultSchedule)
            and self.seed == other.seed
            and self.links == other.links
        )
