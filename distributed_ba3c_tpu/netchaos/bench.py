"""The shared network-phase driver behind chaos_bench --net / pod_bench --net.

One rig shape (docs/netchaos.md): a real localhost pod — one
:class:`PodLearnerPlane`, N supervised ``pod.host`` subprocesses — with a
:class:`NetChaosPlane` interposed on every pod channel via
:meth:`wrap_pod`. The pod is deliberately the topology under test: its
links are the asynchronous DCN-shaped ones (params broadcast, experience
ship) where degraded networks are survivable by design — the lockstep
actor wires pay a full RTT per env step and belong to a host, not a DCN.

Reps this module knows how to run:

- **throughput** (:func:`run_throughput_rep`): ingest-side env-steps/s
  through QUIET proxies (the control arm prices the proxy itself out of
  the gate) vs under a DCN schedule (:func:`dcn_schedule`, e.g. 50 ms
  RTT + 1% loss). Gate: degraded >= 0.85x clean.
- **partition-and-heal** (:func:`run_partition_rep`): all three pod
  links stop moving bytes for a timed window mid-measurement, then heal.
  Recovery must be complete (ingest resumes, the cache re-syncs to the
  current version) with ZERO learner restarts and ZERO host respawns —
  only typed, counted sheds/rejects/backpressure.
- **integrity** (:func:`run_corrupt_rep`): live corruption/truncation
  injection on the experience + params links with CRC framing armed —
  every mangled frame must land as a typed ``corrupt_frame`` reject
  (``pod_corrupt_frames_total`` / ``params_corrupt_total``) while
  training continues.

Every rep embeds the schedule JSON, the injected-event summary and the
seed-replay verdict (:meth:`NetChaosPlane.replay_check`) — the committed
artifact is reproducible from itself.
"""

from __future__ import annotations

import dataclasses
import os
import socket
import time
from typing import Dict, List, Optional, Tuple

from distributed_ba3c_tpu import telemetry
from distributed_ba3c_tpu.netchaos.plane import NetChaosPlane
from distributed_ba3c_tpu.netchaos.schedule import (
    FaultSchedule,
    LinkFaults,
    Partition,
)
from distributed_ba3c_tpu.pod.wire import pod_role
from distributed_ba3c_tpu.utils.serialize import set_wire_crc

#: the pod's three DCN-shaped links, as wrap_pod names them
POD_LINKS = ("params_pub", "params_fetch", "experience")


@dataclasses.dataclass
class NetShape:
    """One rig shape (CI-sized by default; the committed capture scales)."""

    hosts: int = 1
    sims_per_host: int = 2
    segments_per_block: int = 8
    unroll_len: int = 5
    image_size: int = 16
    fc_units: int = 16
    #: host-side staleness bound (0 = ungated host; the partition rep
    #: sheds through the learner gate / link-state machine regardless)
    max_staleness: int = 8
    warmup_timeout: float = 240.0


def free_base() -> Tuple[str, str]:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return f"tcp://127.0.0.1:{port}", f"tcp://127.0.0.1:{port + 1}"


def quiet_schedule(seed: int = 0) -> FaultSchedule:
    """The control arm: proxies pumping, zero faults — the gate compares
    network degradation against the injector's own cost, not against its
    absence."""
    return FaultSchedule({}, seed=seed)


def dcn_schedule(
    rtt_ms: float = 50.0,
    loss: float = 0.01,
    seed: int = 0,
    jitter_frac: float = 0.2,
) -> FaultSchedule:
    """Emulated-DCN faults on every pod link: half the RTT each way,
    proportional jitter, i.i.d. loss."""
    f = LinkFaults(
        latency_ms=rtt_ms / 2.0,
        jitter_ms=rtt_ms / 2.0 * jitter_frac,
        drop=loss,
    )
    return FaultSchedule({name: f for name in POD_LINKS}, seed=seed)


def partition_schedule(
    start_s: float, dur_s: float, seed: int = 0, direction: str = "both"
) -> FaultSchedule:
    """Timed full (or asymmetric) partition of every pod link, relative
    to the rig's post-warmup clock rebase."""
    f = LinkFaults(
        partitions=(Partition(start_s, start_s + dur_s, direction),)
    )
    return FaultSchedule({name: f for name in POD_LINKS}, seed=seed)


def corrupt_schedule(
    corrupt: float = 0.05, truncate: float = 0.05, seed: int = 0
) -> FaultSchedule:
    """Live integrity injection on the data-bearing links."""
    f = LinkFaults(corrupt=corrupt, truncate=truncate)
    return FaultSchedule(
        {"experience": f, "params_pub": f}, seed=seed
    )


class PodNetRig:
    """One pod under one schedule; the rep functions drive it."""

    def __init__(self, shape: NetShape, schedule: FaultSchedule, crc: bool = True):
        from distributed_ba3c_tpu.config import BA3CConfig
        from distributed_ba3c_tpu.orchestrate.pod import (
            PodLearnerPlane,
            PodSupervisor,
            host_argv,
        )

        telemetry.reset_all()
        # CRC framing is armed process-wide AND in the env so the
        # supervised host subprocesses frame their shipped blocks too —
        # and RESTORED at close(): a later same-process phase (pod_bench
        # --net runs before the aggregate phases) must not silently
        # measure with framing it did not ask for
        from distributed_ba3c_tpu.utils.serialize import wire_crc_enabled

        self._prev_crc = wire_crc_enabled()
        self._prev_crc_env = os.environ.get("BA3C_WIRE_CRC")
        if crc:
            set_wire_crc(True)
            os.environ["BA3C_WIRE_CRC"] = "1"
        self.shape = shape
        cfg = BA3CConfig(
            image_size=(shape.image_size, shape.image_size),
            frame_history=4,
            num_actions=4,
            fc_units=shape.fc_units,
            local_time_max=shape.unroll_len,
            predict_batch_size=16,
        )
        c2s, s2c = free_base()
        self.plane = PodLearnerPlane(
            cfg, c2s, s2c,
            max_staleness=shape.max_staleness or None,
        )
        self.plane.start()
        # tight front HWM: the emulated wire holds ~4 blocks in flight, so
        # a partition backs pressure into the HOST's bounds (SNDHWM ->
        # spill -> ship_backpressure_total) instead of hiding inside a
        # 1000-message proxy buffer
        # arm_on_start=False: timed windows stay dormant through the
        # unknowable-length warmup and come live at the post-warmup
        # rebase — so [2s, 12s) means measurement time, not boot time
        self.nc = NetChaosPlane(
            schedule, push_pull_front_hwm=4, arm_on_start=False
        )
        host_base = self.nc.wrap_pod(c2s, s2c)
        self.nc.start()
        self.sup = PodSupervisor(
            shape.hosts,
            lambda i: host_argv(
                i, host_base[0], host_base[1], env="fake",
                n_sims=shape.sims_per_host,
                unroll_len=shape.unroll_len,
                segments_per_block=shape.segments_per_block,
                max_staleness=shape.max_staleness,
                image_size=shape.image_size, frame_history=4,
                num_actions=4, fc_units=shape.fc_units,
            ),
            backoff_base_s=0.25,
        )
        self.sup.start()
        self._quiesced = False
        reg = telemetry.registry("learner")
        self._c_steps = reg.counter("pod_ingest_env_steps_total")
        self._c_blocks = reg.counter("pod_ingest_blocks_total")

    # -- driving ------------------------------------------------------------
    def warmup(self) -> None:
        deadline = time.monotonic() + self.shape.warmup_timeout
        while time.monotonic() < deadline:
            self.plane.step_once(timeout=0.2)
            hosts_up = len([
                r for r in telemetry.all_registries()
                if r.startswith("pod.host")
            ])
            if (
                self._c_blocks.value() >= 2 * self.shape.hosts
                and hosts_up >= self.shape.hosts
            ):
                # the measurement clock starts NOW: partition windows are
                # relative to this rebase, never to the jax-import warmup
                self.nc.rebase_clock()
                return
        try:
            from bench import stall_attribution

            why = stall_attribution()
        except ImportError:
            why = "(bench.py not importable for attribution)"
        raise RuntimeError(
            f"pod produced no warmup blocks from {self.shape.hosts} "
            f"host(s) through netchaos — {why}"
        )

    def drain(self, seconds: float) -> Tuple[float, int]:
        """Drain the learner for ``seconds``; (env-steps/s, blocks)."""
        n0, b0 = self._c_steps.value(), self._c_blocks.value()
        t0 = time.perf_counter()
        deadline = t0 + seconds
        while time.perf_counter() < deadline:
            self.plane.step_once(timeout=0.05)
        dt = time.perf_counter() - t0
        return (
            round((self._c_steps.value() - n0) / dt, 1),
            int(self._c_blocks.value() - b0),
        )

    def measure(self, seconds: float, windows: int) -> List[float]:
        return [self.drain(seconds)[0] for _ in range(max(1, windows))]

    def host_scalars(self, k: int = 0) -> Dict[str, float]:
        return telemetry.registry(pod_role(k)).scalars()

    def learner_scalars(self) -> Dict[str, float]:
        return telemetry.registry("learner").scalars()

    def evidence(self) -> dict:
        """The rep's standing evidence block: schedule, events, replay."""
        ls = self.learner_scalars()
        return {
            "schedule": self.nc.schedule.to_json(),
            "seed": self.nc.schedule.seed,
            "injected": self.nc.summary(),
            "replay": self.nc.replay_check(),
            "publisher_links": self.plane.publisher.link_states(),
            "ingest_blocks": int(ls.get("pod_ingest_blocks_total", 0)),
            "ingest_dropped": int(ls.get("pod_ingest_dropped_total", 0)),
            "ingest_rejected": int(ls.get("pod_ingest_rejected_total", 0)),
            "pod_corrupt_frames": int(ls.get("pod_corrupt_frames_total", 0)),
            "stale_rejected": int(ls.get("stale_blocks_rejected_total", 0)),
            "host0": self.host_scalars(0),
        }

    def quiesce(self) -> None:
        """Stop the traffic sources (hosts, then proxies) and let the
        ingest drain what the pumps flushed. Evidence — the event log,
        the replay diff against live ``_seq`` counters, the typed-reject
        totals — is only race-free AFTER this: a message processed
        between snapshotting events and reading sequence counters would
        read as a spurious seed mismatch."""
        if self._quiesced:
            return
        self._quiesced = True
        self.sup.stop()
        self.sup.join(timeout=5)
        self.sup.close()
        self.nc.stop()
        for p in self.nc.proxies:
            if p.is_alive():
                p.join(timeout=2)
        deadline = time.monotonic() + 3
        while time.monotonic() < deadline:
            if self.plane.step_once(timeout=0.2) is None:
                break

    def close(self) -> None:
        self.quiesce()
        self.nc.close()
        self.plane.close()
        set_wire_crc(self._prev_crc)
        if self._prev_crc_env is None:
            os.environ.pop("BA3C_WIRE_CRC", None)
        else:
            os.environ["BA3C_WIRE_CRC"] = self._prev_crc_env


# ---------------------------------------------------------------------------
# reps
# ---------------------------------------------------------------------------

def run_throughput_rep(
    shape: NetShape,
    schedule: FaultSchedule,
    seconds: float,
    windows: int,
) -> dict:
    rig = PodNetRig(shape, schedule)
    try:
        rig.warmup()
        rates = rig.measure(seconds, windows)
        out = {
            "rate": max(rates),  # best window: the repo's scheduler filter
            "window_rates": rates,
            "updates": int(rig.plane.learner.version),
        }
        rig.quiesce()  # evidence/replay is only race-free on a still rig
        out.update(rig.evidence())
        return out
    finally:
        rig.close()


def run_partition_rep(
    shape: NetShape,
    seed: int,
    pre_s: float = 2.0,
    partition_s: float = 4.0,
    heal_s: float = 8.0,
) -> dict:
    """Full partition of every pod link mid-run, then heal; recovery must
    be restart-free and fully typed."""
    pre_s = max(pre_s, 1.0)  # the drain slack math below needs room
    partition_s = max(partition_s, 2.0)
    schedule = partition_schedule(pre_s, partition_s, seed=seed)
    rig = PodNetRig(shape, schedule)
    out: dict = {"recovered": False}
    try:
        rig.warmup()
        # drains deliberately leave 0.25 s slack around each window
        # boundary: the heal releases a burst of everything the wire and
        # the host's spill held, and measuring it inside the "partition"
        # window would mask the stall the rep exists to show
        pre_rate, pre_blocks = rig.drain(pre_s - 0.25)
        v_at_partition = int(rig.plane.learner.version)
        rig.drain(0.5)  # spans the partition-start boundary, discarded
        part_rate, part_blocks = rig.drain(partition_s - 1.0)
        rig.drain(0.75)  # spans the heal boundary, discarded
        heal_rate, heal_blocks = rig.drain(heal_s)
        # the killed-link rejoin proof: the host's mirrored params_version
        # must pass the partition-time publish frontier after the heal
        deadline = time.monotonic() + 60
        rejoined = None
        while time.monotonic() < deadline:
            rig.plane.step_once(timeout=0.2)
            v = rig.host_scalars(0).get("params_version", -1)
            if v >= v_at_partition:
                rejoined = v
                break
        rig.quiesce()  # evidence/replay is only race-free on a still rig
        orch = telemetry.registry("orchestrator").scalars()
        host0 = rig.host_scalars(0)
        out.update({
            "pre": {"rate": pre_rate, "blocks": pre_blocks},
            "partition": {"rate": part_rate, "blocks": part_blocks},
            "heal": {"rate": heal_rate, "blocks": heal_blocks},
            "version_at_partition": v_at_partition,
            "rejoined_at_version": rejoined,
            "learner_restarts": int(orch.get("learner_restarts_total", 0)),
            "host_respawns": int(orch.get("server_respawns_total", 0)),
            "ship_backpressure": int(
                host0.get("ship_backpressure_total", 0)
            ),
            "shipped_dropped": int(host0.get("shipped_dropped_total", 0)),
            "fetch_retries": int(
                host0.get("params_fetch_retries_total", 0)
            ),
        })
        out.update(rig.evidence())
        out["recovered"] = bool(
            rejoined is not None
            and heal_blocks > 0
            # the partition actually STALLED the link (< half the clean
            # rate strictly inside the window; ~0 in practice)
            and part_rate < 0.5 * max(pre_rate, 1.0)
            and out["learner_restarts"] == 0
            and out["host_respawns"] == 0
        )
        return out
    finally:
        rig.close()


def run_corrupt_rep(
    shape: NetShape, seed: int, seconds: float = 6.0
) -> dict:
    """Live corruption/truncation against CRC-armed codecs: every mangled
    frame is a typed reject, training continues."""
    rig = PodNetRig(shape, corrupt_schedule(seed=seed), crc=True)
    try:
        rig.warmup()
        rate, blocks = rig.drain(seconds)
        rig.quiesce()  # every in-flight mangled frame delivered + decoded
        out = {"rate": rate, "blocks": blocks}
        out.update(rig.evidence())
        injected = out["injected"]
        mangled = injected.get("corrupt", 0) + injected.get("truncate", 0)
        # the gate is EVERY-frame-typed on the lossless link: experience
        # mangles all reach the bound PULL ingest after the quiesce, so
        # pod typed rejects must cover them one-for-one. params_pub
        # mangles can be legitimately shed by SUB HWM before delivery —
        # their typed counters are evidence, not a 1:1 bound.
        exp_mangled = sum(
            1 for e in rig.nc.events()
            if e["link"] == "experience" and e["kind"] in ("corrupt", "truncate")
        )
        pod_typed = out["pod_corrupt_frames"] + out["ingest_rejected"]
        typed = pod_typed + int(
            out["host0"].get("params_corrupt_total", 0)
        ) + int(
            out["host0"].get("params_malformed_total", 0)
        )
        out["injected_mangled"] = mangled
        out["experience_mangled"] = exp_mangled
        out["typed_rejects"] = typed
        out["all_typed"] = bool(
            mangled > 0
            and blocks > 0
            and exp_mangled > 0
            and pod_typed >= exp_mangled
        )
        return out
    finally:
        rig.close()
