"""In-process ZMQ proxy pumps that apply a FaultSchedule to one link.

Three proxy shapes cover every socket pair in the fleet/pod port map
(docs/netchaos.md):

- :class:`PushPullProxy` — PUSH clients -> PULL server (the fleet's c2s
  experience pipe, the pod's experience channel). One ``fwd`` pump.
- :class:`PubProxy` — PUB server -> SUB clients (the pod's params
  broadcast), as the classic XSUB/XPUB relay: data pumps ``rev`` with
  faults, subscription control frames pass upstream untouched.
- :class:`RouterProxy` — DEALER clients <-> ROUTER server (the fleet's
  s2c action pipe, the pod's params fetch). Identity-preserving: the
  front ROUTER faces the clients, and the proxy materializes ONE back
  DEALER per observed client identity so the real server sees each
  client under its own ident (ROUTER_HANDOVER keeps working, replies
  route correctly). Idents are learned from ``fwd`` traffic, or handed
  in from outside via :meth:`RouterProxy.ensure_ident` for channels the
  clients never speak on (the s2c action pipe — its idents are sniffed
  off the paired c2s proxy's messages by the plane).

Every proxy is one StoppableThread with a Poller loop and a delay heap:
latency/jitter/bandwidth faults schedule a message's release time,
discrete faults (drop/corrupt/truncate/reorder) come from the schedule's
pure per-sequence decision, partitions silence a direction for their
window, and every injected event is reported to the owning plane — the
flight-recorded, seed-replayable account the bench gates diff against.
"""

from __future__ import annotations

import heapq
import itertools
import time
from typing import Callable, Dict, List, Optional, Tuple

import zmq

from distributed_ba3c_tpu.netchaos.schedule import FaultSchedule
from distributed_ba3c_tpu.utils.concurrency import StoppableThread

#: poller tick while idle (ms): bounds fault-release latency jitter the
#: injector itself adds on top of the scheduled delay
_TICK_MS = 10


def _mutate_frames(
    frames: List[bytes], offset_u: float, flip: bool
) -> Tuple[List[bytes], bool]:
    """Corrupt (one bit) or truncate the LARGEST frame — with the block
    wire that is always an array payload, the exact case the receiving
    codec's CRC must catch before any ``frombuffer``. Returns (frames,
    applied); empty messages pass through unfaulted."""
    if not frames:
        return frames, False
    i = max(range(len(frames)), key=lambda j: len(frames[j]))
    buf = bytearray(frames[i])
    if not buf:
        return frames, False
    pos = int(offset_u * len(buf)) % len(buf)
    if flip:
        buf[pos] ^= 1 << (pos % 8)
        out = bytes(buf)
    else:
        out = bytes(buf[:pos])
    frames = list(frames)
    frames[i] = out
    return frames, True


class LinkProxy(StoppableThread):
    """Base pump: sequence accounting, fault application, delay heap."""

    def __init__(self, link: str, schedule: FaultSchedule, plane, name: str):
        super().__init__(daemon=True, name=name)
        self.link = link
        self.schedule = schedule
        self.plane = plane
        self._faults = schedule.faults_for(link)
        self._seq = {"fwd": 0, "rev": 0}
        self._last_due = {"fwd": 0.0, "rev": 0.0}
        self._bw_free = {"fwd": 0.0, "rev": 0.0}
        self._part_state = {"fwd": False, "rev": False}
        self._pending: List[tuple] = []  # (due, tiebreak, send, frames, seq)
        self._tiebreak = itertools.count()

    def _partitioned(self, direction: str) -> bool:
        """Partition = the link STOPS MOVING BYTES: the proxy refuses to
        drain that direction's intake, so the real sender's own bounds
        engage exactly as they would against a dead DCN path — the
        shipper's SNDHWM + spill, PUB's slow-subscriber shedding, the
        cache's fetch backoff. (The probabilistic ``drop`` fault is the
        other model: packet loss on a LIVE link — received and
        discarded.) Window entry/exit is recorded once per transition
        (seq -1: partitions are time-masked, not RNG-replayed)."""
        if not self._faults.partitions:
            return False
        p = self.schedule.partitioned(self.link, direction, self.plane.t_rel())
        if p != self._part_state[direction]:
            self._part_state[direction] = p
            self.plane.event(
                self.link, direction, -1,
                "partition_start" if p else "partition_heal",
            )
        return p

    # -- the injection core -------------------------------------------------
    def _process(
        self,
        direction: str,
        frames: List[bytes],
        send: Callable[[List[bytes], int], None],
    ) -> None:
        seq = self._seq[direction]
        self._seq[direction] = seq + 1
        f = self._faults
        if f.quiet():
            send(frames, seq)  # clean arm: zero decisions, zero heap
            return
        d = self.schedule.decide(self.link, direction, seq)
        if d.drop:
            self.plane.event(self.link, direction, seq, "drop")
            return
        if d.corrupt:
            frames, _ = _mutate_frames(frames, d.offset_u, flip=True)
            # recorded whether or not bytes changed (an all-empty message
            # has nothing to flip): the log replays the DECISION stream,
            # and an unlogged decision would read as a seed mismatch
            self.plane.event(self.link, direction, seq, "corrupt")
        elif d.truncate:
            frames, _ = _mutate_frames(frames, d.offset_u, flip=False)
            self.plane.event(self.link, direction, seq, "truncate")
        delay = f.latency_ms / 1e3 + d.jitter_u * f.jitter_ms / 1e3
        if d.reorder:
            extra = f.reorder_extra_ms or (f.latency_ms + f.jitter_ms + 5.0)
            delay += extra / 1e3
            self.plane.event(self.link, direction, seq, "reorder")
        now = time.monotonic()
        if f.bandwidth_kbps:
            size = sum(len(b) for b in frames)
            transmit = size * 8 / (f.bandwidth_kbps * 1e3)
            start = max(now, self._bw_free[direction])
            self._bw_free[direction] = start + transmit
            due = start + transmit + delay
        else:
            due = now + delay
        if not d.reorder:
            # FIFO under jitter: a message never overtakes its
            # predecessor unless the schedule explicitly reordered it
            due = max(due, self._last_due[direction])
            self._last_due[direction] = due
        if due <= now and not self._pending:
            send(frames, seq)
            return
        heapq.heappush(
            self._pending, (due, next(self._tiebreak), send, frames, seq)
        )

    def _flush_due(self) -> None:
        now = time.monotonic()
        while self._pending and self._pending[0][0] <= now:
            _, _, send, frames, seq = heapq.heappop(self._pending)
            send(frames, seq)

    def _poll_timeout_ms(self) -> int:
        if not self._pending:
            return _TICK_MS
        wait = self._pending[0][0] - time.monotonic()
        return max(0, min(_TICK_MS, int(wait * 1e3)))

    def _flush_all(self) -> None:
        """Teardown: release everything still in flight immediately (the
        delayed bytes were 'on the wire'; closing the proxy is not a
        partition)."""
        while self._pending:
            _, _, send, frames, seq = heapq.heappop(self._pending)
            try:
                send(frames, seq)
            except zmq.ZMQError:
                return

    def _overflow(self, direction: str, seq: int) -> None:
        """A back/front socket refused the pumped message (its HWM bit):
        accounted as its own event kind — the proxy never blocks."""
        self.plane.event(self.link, direction, seq, "overflow")

    def close(self) -> None:
        self.stop()
        if self.is_alive():
            self.join(timeout=2)


class PushPullProxy(LinkProxy):
    """PUSH clients -> [front PULL | back PUSH] -> PULL server."""

    def __init__(
        self,
        link: str,
        schedule: FaultSchedule,
        plane,
        front_addr: str,
        back_addr: str,
        context: zmq.Context,
        on_message: Optional[Callable[[List[bytes]], None]] = None,
        front_hwm: int = 64,
    ):
        super().__init__(link, schedule, plane, name=f"netchaos-{link}")
        self.front_addr, self.back_addr = front_addr, back_addr
        self._on_message = on_message
        self._front = context.socket(zmq.PULL)
        self._front.setsockopt(zmq.LINGER, 0)
        # the front RCVHWM models the bytes "in flight" on the emulated
        # wire: during a partition hold, anything past it backs up into
        # the SENDER's own bounds (SNDHWM -> spill -> typed backpressure)
        # — a 1000-message default would hide exactly the behavior the
        # partition rep exists to exercise
        self._front.setsockopt(zmq.RCVHWM, max(1, int(front_hwm)))
        self._front.bind(front_addr)
        self._back = context.socket(zmq.PUSH)
        self._back.setsockopt(zmq.LINGER, 0)
        # bounded like every transport socket in this repo: a partitioned
        # real server turns into counted 'overflow' events here, never
        # unbounded proxy memory
        self._back.setsockopt(zmq.SNDHWM, 64)
        self._back.connect(back_addr)

    def _send_back(self, frames: List[bytes], seq: int) -> None:
        try:
            self._back.send_multipart(frames, zmq.NOBLOCK)
        except zmq.Again:
            self._overflow("fwd", seq)

    def run(self) -> None:
        poller = zmq.Poller()
        poller.register(self._front, zmq.POLLIN)
        try:
            while not self.stopped():
                events = dict(poller.poll(self._poll_timeout_ms()))
                if self._front in events:
                    if self._partitioned("fwd"):
                        # hold, don't drain: the sender's bounds must bite
                        time.sleep(_TICK_MS / 1e3)
                    else:
                        frames = self._front.recv_multipart()
                        if self._on_message is not None:
                            self._on_message(frames)
                        self._process("fwd", frames, self._send_back)
                self._flush_due()
            self._flush_all()
        except (zmq.ContextTerminated, zmq.ZMQError):
            return


class PubProxy(LinkProxy):
    """PUB server -> [back XSUB | front XPUB] -> SUB clients."""

    def __init__(
        self,
        link: str,
        schedule: FaultSchedule,
        plane,
        front_addr: str,
        back_addr: str,
        context: zmq.Context,
    ):
        super().__init__(link, schedule, plane, name=f"netchaos-{link}")
        self.front_addr, self.back_addr = front_addr, back_addr
        self._front = context.socket(zmq.XPUB)
        self._front.setsockopt(zmq.LINGER, 0)
        self._front.setsockopt(zmq.SNDHWM, 16)
        self._front.bind(front_addr)
        self._back = context.socket(zmq.XSUB)
        self._back.setsockopt(zmq.LINGER, 0)
        self._back.connect(back_addr)

    def _send_front(self, frames: List[bytes], seq: int) -> None:
        try:
            self._front.send_multipart(frames, zmq.NOBLOCK)
        except zmq.Again:
            self._overflow("rev", seq)

    def run(self) -> None:
        poller = zmq.Poller()
        poller.register(self._front, zmq.POLLIN)
        poller.register(self._back, zmq.POLLIN)
        try:
            while not self.stopped():
                events = dict(poller.poll(self._poll_timeout_ms()))
                if self._back in events:
                    if self._partitioned("rev"):
                        # hold: the real PUB's slow-subscriber HWM sheds
                        # broadcasts upstream, exactly a dead DCN path
                        time.sleep(_TICK_MS / 1e3)
                    else:
                        # published data: the faulted direction
                        self._process(
                            "rev", self._back.recv_multipart(),
                            self._send_front,
                        )
                if self._front in events:
                    # subscription control frames flow upstream untouched
                    # (faulting them would silently unsubscribe a healthy
                    # host — not a network fault, a broken injector)
                    try:
                        self._back.send_multipart(
                            self._front.recv_multipart(), zmq.NOBLOCK
                        )
                    except zmq.Again:
                        pass
                self._flush_due()
            self._flush_all()
        except (zmq.ContextTerminated, zmq.ZMQError):
            return


class RouterProxy(LinkProxy):
    """DEALER clients <-> [front ROUTER | per-ident back DEALERs] <-> ROUTER
    server, identity-preserving both ways."""

    def __init__(
        self,
        link: str,
        schedule: FaultSchedule,
        plane,
        front_addr: str,
        back_addr: str,
        context: zmq.Context,
    ):
        super().__init__(link, schedule, plane, name=f"netchaos-{link}")
        self.front_addr, self.back_addr = front_addr, back_addr
        self._context = context
        self._front = context.socket(zmq.ROUTER)
        self._front.setsockopt(zmq.LINGER, 0)
        # respawned clients reconnect under slot-stable idents — the same
        # HANDOVER contract the real masters run (docs/actor_plane.md)
        self._front.setsockopt(zmq.ROUTER_HANDOVER, 1)
        self._front.bind(front_addr)
        self._dealers: Dict[bytes, zmq.Socket] = {}
        import collections

        self._new_idents: "collections.deque[bytes]" = collections.deque()
        self._poller = zmq.Poller()
        self._poller.register(self._front, zmq.POLLIN)

    def ensure_ident(self, ident: bytes) -> None:
        """Register a client identity from OUTSIDE the pump thread (the
        plane's c2s sniffer feeding the s2c proxy): the back DEALER for it
        is materialized inside the loop — sockets stay single-threaded."""
        if ident and ident not in self._dealers:
            self._new_idents.append(bytes(ident))

    def _ensure_now(self, ident: bytes):
        sock = self._dealers.get(ident)
        if sock is None:
            sock = self._context.socket(zmq.DEALER)
            sock.setsockopt(zmq.LINGER, 0)
            sock.setsockopt(zmq.IDENTITY, ident)
            sock.connect(self.back_addr)
            self._dealers[ident] = sock
            self._poller.register(sock, zmq.POLLIN)
        return sock

    def _send_back(self, ident: bytes):
        def send(frames: List[bytes], seq: int) -> None:
            try:
                self._ensure_now(ident).send_multipart(frames, zmq.NOBLOCK)
            except zmq.Again:
                self._overflow("fwd", seq)

        return send

    def _send_front(self, ident: bytes):
        def send(frames: List[bytes], seq: int) -> None:
            try:
                self._front.send_multipart([ident] + frames, zmq.NOBLOCK)
            except zmq.Again:
                self._overflow("rev", seq)

        return send

    def run(self) -> None:
        try:
            while not self.stopped():
                while self._new_idents:
                    self._ensure_now(self._new_idents.popleft())
                events = dict(self._poller.poll(self._poll_timeout_ms()))
                held = False
                if self._front in events:
                    if self._partitioned("fwd"):
                        held = True
                    else:
                        frames = self._front.recv_multipart()
                        ident, payload = frames[0], frames[1:]
                        self._ensure_now(ident)
                        self._process("fwd", payload, self._send_back(ident))
                # per-ident back sockets ARE the identity-preserving proxy
                # structure (one DEALER per client so the real ROUTER sees
                # true idents) — not a per-env data wire
                for ident, sock in list(self._dealers.items()):
                    if sock in events:
                        if self._partitioned("rev"):
                            held = True
                            break
                        self._process(
                            "rev", sock.recv_multipart(),  # ba3clint: disable=A6 — ident-preserving proxy fan-in
                            self._send_front(ident),
                        )
                if held:
                    time.sleep(_TICK_MS / 1e3)
                self._flush_due()
            self._flush_all()
        except (zmq.ContextTerminated, zmq.ZMQError):
            return
