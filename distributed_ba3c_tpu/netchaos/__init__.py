"""Netchaos: a deterministic network fault-injection plane (docs/netchaos.md).

Everything the repo had proven about failure before this package was
process death on loopback wires that never delay, drop or partition
(ROADMAP item 2's named debt). Netchaos closes it: seeded, per-link
fault schedules (:mod:`schedule`) applied by in-process ZMQ proxy pumps
(:mod:`proxy`) interposed on any link the fleet/pod port map derives
(:mod:`plane` — hand a process a proxied base pipe pair and every
derived channel routes through the injector unchanged). Latency (fixed +
jitter), probabilistic drop, bandwidth caps, reorder, frame
truncation/corruption, and timed full/asymmetric partitions; every
injected event is flight-recorded with the schedule seed and the whole
event log is re-derivable from that seed (``NetChaosPlane.replay_check``)
— a failing rep replays exactly.

The hardening it forced lives in the transports themselves: CRC32 wire
framing with typed ``corrupt_frame`` rejects (utils/serialize.py),
heartbeat-driven per-link ``up -> degraded -> partitioned`` state
machines (pod/linkstate.py) on the params cache/publisher and the
experience shipper, bounded reconnect/backoff with the epoch-stamp
rejoin contract, and degraded-mode semantics: a params-partitioned host
sheds through the staleness gate, a shipper against a partitioned ingest
spills to a bounded drop-oldest buffer — rollout never wedges.

Gates: ``scripts/chaos_bench.py --net`` (throughput under 50 ms RTT + 1%
loss >= 0.85x clean; partition-and-heal with zero learner restarts) and
``scripts/pod_bench.py --net`` (the emulated-DCN rows,
``runs/netchaos_bench_r14.json``).
"""

from __future__ import annotations

from distributed_ba3c_tpu.netchaos.schedule import (  # noqa: F401
    DIRECTIONS,
    RNG_KINDS,
    Decision,
    FaultSchedule,
    LinkFaults,
    Partition,
)
from distributed_ba3c_tpu.netchaos.proxy import (  # noqa: F401
    LinkProxy,
    PubProxy,
    PushPullProxy,
    RouterProxy,
)
from distributed_ba3c_tpu.netchaos.plane import (  # noqa: F401
    MASK_KINDS,
    NetChaosPlane,
)
