// Batched Atari-like environment core (native).
//
// The reference's principal native component is ALE — a C++ Atari 2600
// emulator whose per-frame cost dominates the actor side (SURVEY.md §2.10).
// This is its TPU-rebuild equivalent: game physics, frameskip and 84x84
// grayscale rendering in C++, with a BATCHED step API so one host process
// drives hundreds of envs per call (the reference paid one process per env).
//
// Game semantics intentionally mirror distributed_ba3c_tpu/envs/jaxenv/
// (pong.py, breakout.py): same geometry constants, action maps, reward
// structure (first-to-21 Pong; 6x18 bricks / 5 lives / row-scored Breakout),
// so policies transfer between the on-device JAX envs and this host-side
// core, and the Python tests can assert semantic parity.
//
// No external dependencies (the image has no zmq.h/msgpack.h): transport is
// thin pyzmq glue in distributed_ba3c_tpu/envs/native.py; every hot cycle
// (step physics + render) happens here.
//
// Build: make -C cpp   (g++ -O3 -shared -fPIC)

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <memory>
#include <random>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr int kH = 84, kW = 84;
constexpr int kFrameSkip = 4;

// ---------------------------------------------------------------- Pong ----
namespace pong {
constexpr float kPaddleH = 0.16f, kPaddleW = 0.02f;
constexpr float kAgentX = 0.95f, kOppX = 0.05f;
constexpr float kBallR = 0.015f;
constexpr float kPaddleSpeed = 0.05f, kOppSpeed = 0.035f, kBallSpeed = 0.04f;
constexpr int kWinScore = 21;
constexpr int kNumActions = 6;
}  // namespace pong

// ------------------------------------------------------------ Seaquest ----
// Mirrors distributed_ba3c_tpu/envs/jaxenv/seaquest.py: 4 enemy lanes,
// horizontal torpedoes, oxygen meter with surfacing, 3 lives.
namespace sq {
constexpr int kLanes = 4;
constexpr float kLaneY[kLanes] = {0.35f, 0.5f, 0.65f, 0.8f};
constexpr float kSurfaceY = 0.15f;
constexpr float kSubSpeed = 0.03f, kFishSpeed = 0.02f, kTorpSpeed = 0.08f;
constexpr float kSubR = 0.03f, kFishR = 0.025f;
constexpr float kOxyMax = 200.f, kOxyRefill = 8.f;
constexpr int kLives = 3;
constexpr float kFishPoints = 20.f;
constexpr int kMaxT = 5000;
constexpr int kNumActions = 6;
}  // namespace sq

// --------------------------------------------------------------- Q*bert ---
// Mirrors distributed_ba3c_tpu/envs/jaxenv/qbert.py: 21-cube pyramid,
// +25/new cube, +100/board clear, bouncing enemy ball, 3 lives, 1 hop/step.
namespace qb {
constexpr int kRows = 6;
constexpr int kCubes = kRows * (kRows + 1) / 2;  // 21
constexpr float kCubePoints = 25.f, kClearBonus = 100.f;
constexpr int kLives = 3, kMaxT = 2000, kNumActions = 5;
}  // namespace qb

// ------------------------------------------------------ Space Invaders ----
// Mirrors distributed_ba3c_tpu/envs/jaxenv/space_invaders.py: 6x6 fleet,
// row-scored 30..5, one player shot, alien bombs, 3 lives.
namespace si {
constexpr int kRows = 6, kCols = 6;
constexpr float kAlienW = 0.07f, kAlienH = 0.03f;
constexpr float kGridDX = 0.11f, kGridDY = 0.07f;
constexpr float kMarch = 0.004f, kDescend = 0.05f;
constexpr float kPlayerY = 0.93f, kPlayerW = 0.05f, kPlayerSpeed = 0.03f;
constexpr float kShotSpeed = 0.05f, kBombSpeed = 0.025f, kBombP = 0.06f;
constexpr int kNBombs = 3, kLives = 3, kMaxT = 10000, kNumActions = 6;
constexpr float kRowPoints[kRows] = {30.f, 25.f, 20.f, 15.f, 10.f, 5.f};
}  // namespace si

// -------------------------------------------------------------- Boxing ----
// Mirrors distributed_ba3c_tpu/envs/jaxenv/boxing.py: +1/-1 per punch
// landed/taken, KO at 100, pursuing scripted opponent, 18 actions.
namespace bx {
constexpr float kRingLo = 0.08f, kRingHi = 0.92f;
constexpr float kMove = 0.022f, kOppMove = 0.014f;
constexpr float kPunchRange = 0.10f, kOppPunchP = 0.25f;
constexpr int kPunchCd = 4, kKo = 100, kMaxT = 2000, kNumActions = 18;
}  // namespace bx

// ------------------------------------------------------------- Assault ----
// Mirrors distributed_ba3c_tpu/envs/jaxenv/assault.py: mothership + 3
// attacker lanes, 21-point quanta, cannon heat/jam, 4 lives, 7 actions.
namespace as_ {
constexpr int kNLanes = 3;
constexpr float kLaneX[kNLanes] = {0.25f, 0.5f, 0.75f};
constexpr float kMotherY = 0.08f, kMotherW = 0.10f, kMotherSpeed = 0.006f;
constexpr float kAttW = 0.035f, kAttH = 0.025f;
constexpr float kDescend = 0.008f, kStrafe = 0.006f, kSpawnP = 0.08f;
constexpr float kPlayerY = 0.93f, kPlayerW = 0.05f, kPlayerSpeed = 0.03f;
constexpr float kShotSpeed = 0.06f, kBombSpeed = 0.02f, kBombP = 0.04f;
constexpr float kHeatPerShot = 0.45f, kCool = 0.015f, kVentCool = 0.12f;
constexpr int kLives = 4, kMaxT = 10000, kNumActions = 7;
constexpr float kAttackerPoints = 21.f, kMotherPoints = 42.f;
}  // namespace as_

// ------------------------------------------------------------ Breakout ----
namespace brk {
constexpr int kRows = 6, kCols = 18;
constexpr float kBrickTop = 0.15f, kBrickH = 0.03f;
constexpr float kPaddleY = 0.92f, kPaddleH = 0.02f, kPaddleW = 0.08f;
constexpr float kBallR = 0.012f;
constexpr float kPaddleSpeed = 0.04f, kBallSpeed = 0.035f;
constexpr int kLives = 5;
constexpr int kMaxT = 10000;
constexpr int kNumActions = 4;
constexpr float kRowPoints[kRows] = {7.f, 7.f, 4.f, 4.f, 1.f, 1.f};
}  // namespace brk

struct StepOut {
  float reward = 0.f;
  bool done = false;
};

// clamped-rect rasterizer shared by all games
void DrawRect(uint8_t* obs, float cx, float cy, float hw, float hh, uint8_t v) {
  int x0 = std::max(0, (int)std::floor((cx - hw) * kW));
  int x1 = std::min(kW - 1, (int)std::ceil((cx + hw) * kW));
  int y0 = std::max(0, (int)std::floor((cy - hh) * kH));
  int y1 = std::min(kH - 1, (int)std::ceil((cy + hh) * kH));
  for (int y = y0; y <= y1; ++y)
    for (int x = x0; x <= x1; ++x) obs[y * kW + x] = v;
}

class Env {
 public:
  virtual ~Env() = default;
  virtual void Reset() = 0;
  virtual StepOut Step(int action) = 0;  // one agent step (kFrameSkip ticks)
  virtual void Render(uint8_t* obs) const = 0;  // [kH * kW]
  virtual int NumActions() const = 0;
};

class PongEnv : public Env {
 public:
  explicit PongEnv(uint64_t seed) : rng_(seed) { Reset(); }

  void Reset() override {
    agent_y_ = opp_y_ = 0.5f;
    agent_score_ = opp_score_ = 0;
    Serve(/*towards_agent=*/true);
  }

  StepOut Step(int action) override {
    float move = 0.f;
    if (action == 2 || action == 4) move = -1.f;
    if (action == 3 || action == 5) move = 1.f;
    StepOut out;
    for (int i = 0; i < kFrameSkip; ++i) out.reward += Substep(move);
    if (agent_score_ >= pong::kWinScore || opp_score_ >= pong::kWinScore) {
      out.done = true;
      Reset();
    }
    return out;
  }

  void Render(uint8_t* obs) const override {
    std::memset(obs, 0, kH * kW);
    // walls
    for (int x = 0; x < kW; ++x) {
      obs[0 * kW + x] = obs[1 * kW + x] = 80;
      obs[(kH - 1) * kW + x] = obs[(kH - 2) * kW + x] = 80;
    }
    DrawRect(obs, bx_, by_, pong::kBallR, pong::kBallR, 255);
    DrawRect(obs, pong::kAgentX, agent_y_, pong::kPaddleW, pong::kPaddleH / 2, 255);
    DrawRect(obs, pong::kOppX, opp_y_, pong::kPaddleW, pong::kPaddleH / 2, 255);
  }

  int NumActions() const override { return pong::kNumActions; }

  int agent_score() const { return agent_score_; }
  int opp_score() const { return opp_score_; }

 private:
  void Serve(bool towards_agent) {
    std::uniform_real_distribution<float> ang(-0.7f, 0.7f);
    std::uniform_real_distribution<float> jit(-0.1f, 0.1f);
    float a = ang(rng_);
    bx_ = 0.5f;
    by_ = 0.5f + jit(rng_);
    vx_ = pong::kBallSpeed * std::cos(a) * (towards_agent ? 1.f : -1.f);
    vy_ = pong::kBallSpeed * std::sin(a);
  }

  float Substep(float move) {
    namespace P = pong;
    agent_y_ = std::clamp(agent_y_ + move * P::kPaddleSpeed, P::kPaddleH / 2,
                          1.f - P::kPaddleH / 2);
    float opp_dy = std::clamp(by_ - opp_y_, -P::kOppSpeed, P::kOppSpeed);
    opp_y_ = std::clamp(opp_y_ + opp_dy, P::kPaddleH / 2, 1.f - P::kPaddleH / 2);

    bx_ += vx_;
    by_ += vy_;
    if (by_ < P::kBallR || by_ > 1.f - P::kBallR) {
      vy_ = -vy_;
      by_ = std::clamp(by_, P::kBallR, 1.f - P::kBallR);
    }
    // agent paddle (right, ball moving right)
    if (vx_ > 0 && bx_ >= P::kAgentX - P::kPaddleW &&
        std::fabs(by_ - agent_y_) <= P::kPaddleH / 2 + P::kBallR) {
      float off = (by_ - agent_y_) / (P::kPaddleH / 2);
      vx_ = -vx_;
      vy_ = P::kBallSpeed * 0.9f * off;
      bx_ = P::kAgentX - P::kPaddleW - P::kBallR;
    }
    // opponent paddle (left, ball moving left)
    if (vx_ < 0 && bx_ <= P::kOppX + P::kPaddleW &&
        std::fabs(by_ - opp_y_) <= P::kPaddleH / 2 + P::kBallR) {
      float off = (by_ - opp_y_) / (P::kPaddleH / 2);
      vx_ = -vx_;
      vy_ = P::kBallSpeed * 0.9f * off;
      bx_ = P::kOppX + P::kPaddleW + P::kBallR;
    }
    float reward = 0.f;
    if (bx_ <= 0.f) {  // opponent missed
      reward = 1.f;
      ++agent_score_;
      Serve(/*towards_agent=*/false);
    } else if (bx_ >= 1.f) {  // agent missed
      reward = -1.f;
      ++opp_score_;
      Serve(/*towards_agent=*/true);
    }
    return reward;
  }

  std::mt19937_64 rng_;
  float bx_, by_, vx_, vy_, agent_y_, opp_y_;
  int agent_score_, opp_score_;
};

class BreakoutEnv : public Env {
 public:
  explicit BreakoutEnv(uint64_t seed) : rng_(seed) { Reset(); }

  void Reset() override {
    paddle_x_ = 0.5f;
    bx_ = 0.5f;
    by_ = brk::kPaddleY - 0.05f;
    vx_ = vy_ = 0.f;
    lives_ = brk::kLives;
    in_play_ = false;
    t_ = 0;
    std::fill(std::begin(bricks_), std::end(bricks_), true);
  }

  StepOut Step(int action) override {
    float move = action == 2 ? 1.f : action == 3 ? -1.f : 0.f;
    bool fire = action == 1;
    StepOut out;
    for (int i = 0; i < kFrameSkip; ++i) out.reward += Substep(move, fire);
    ++t_;
    if (lives_ <= 0 || t_ >= brk::kMaxT) {
      out.done = true;
      Reset();
    }
    return out;
  }

  void Render(uint8_t* obs) const override {
    namespace B = brk;
    std::memset(obs, 0, kH * kW);
    for (int x = 0; x < kW; ++x) obs[0 * kW + x] = obs[1 * kW + x] = 80;
    // bricks
    for (int r = 0; r < B::kRows; ++r) {
      int y0 = (int)std::floor((B::kBrickTop + r * B::kBrickH) * kH);
      int y1 = (int)std::floor((B::kBrickTop + (r + 1) * B::kBrickH) * kH) - 1;
      for (int c = 0; c < B::kCols; ++c) {
        if (!bricks_[r * B::kCols + c]) continue;
        int x0 = c * kW / B::kCols;
        int x1 = (c + 1) * kW / B::kCols - 1;
        for (int y = std::max(0, y0); y <= std::min(kH - 1, y1); ++y)
          for (int x = x0; x <= x1; ++x) obs[y * kW + x] = 180;
      }
    }
    DrawRect(obs, bx_, by_, B::kBallR, B::kBallR, 255);
    DrawRect(obs, paddle_x_, B::kPaddleY, B::kPaddleW / 2, B::kPaddleH, 255);
  }

  int NumActions() const override { return brk::kNumActions; }
  int lives() const { return lives_; }
  int bricks_left() const {
    int n = 0;
    for (bool b : bricks_) n += b;
    return n;
  }

 private:
  float Substep(float move, bool fire) {
    namespace B = brk;
    paddle_x_ = std::clamp(paddle_x_ + move * B::kPaddleSpeed, B::kPaddleW / 2,
                           1.f - B::kPaddleW / 2);
    if (!in_play_) {
      bx_ = paddle_x_;
      by_ = B::kPaddleY - 0.05f;
      if (fire) {
        std::uniform_real_distribution<float> ang(0.25f * (float)M_PI,
                                                  0.75f * (float)M_PI);
        float a = ang(rng_);
        vx_ = B::kBallSpeed * std::cos(a);
        vy_ = -B::kBallSpeed * std::sin(a);
        in_play_ = true;
      }
      return 0.f;
    }
    bx_ += vx_;
    by_ += vy_;
    if (bx_ < B::kBallR || bx_ > 1.f - B::kBallR) {
      vx_ = -vx_;
      bx_ = std::clamp(bx_, B::kBallR, 1.f - B::kBallR);
    }
    if (by_ < B::kBallR) {
      vy_ = -vy_;
      by_ = B::kBallR;
    }
    // paddle
    if (vy_ > 0 && by_ >= B::kPaddleY - B::kPaddleH &&
        std::fabs(bx_ - paddle_x_) <= B::kPaddleW / 2 + B::kBallR) {
      float off = (bx_ - paddle_x_) / (B::kPaddleW / 2);
      vx_ = B::kBallSpeed * off;
      vy_ = -std::fabs(vy_);
      by_ = B::kPaddleY - B::kPaddleH - B::kBallR;
    }
    // bricks
    float reward = 0.f;
    int row = (int)std::floor((by_ - B::kBrickTop) / B::kBrickH);
    int col = (int)std::floor(bx_ * B::kCols);
    if (row >= 0 && row < B::kRows && col >= 0 && col < B::kCols &&
        bricks_[row * B::kCols + col]) {
      bricks_[row * B::kCols + col] = false;
      reward = B::kRowPoints[row];
      // reflect AND expel (see jaxenv/breakout.py: the drilling bug)
      bool from_below = vy_ < 0;
      by_ = from_below ? B::kBrickTop + (row + 1) * B::kBrickH + B::kBallR
                       : B::kBrickTop + row * B::kBrickH - B::kBallR;
      vy_ = -vy_;
      if (bricks_left() == 0)
        std::fill(std::begin(bricks_), std::end(bricks_), true);
    }
    // ball lost
    if (by_ >= 1.f - 1e-6f) {
      --lives_;
      in_play_ = false;
      vx_ = vy_ = 0.f;
      bx_ = paddle_x_;
      by_ = B::kPaddleY - 0.05f;
    }
    return reward;
  }

  std::mt19937_64 rng_;
  float bx_, by_, vx_, vy_, paddle_x_;
  bool bricks_[brk::kRows * brk::kCols];
  int lives_, t_;
  bool in_play_;
};

// jax-parity rasterizer: pixel-center inequality |Xc-cx|<=hw in float32,
// EXACTLY as the jnp renders evaluate it (envs/jaxenv/seaquest.py etc.) —
// closed-form ceil/floor bounds can disagree by one boundary pixel because
// (cx+hw)*kW and (x+0.5)/kW round differently in float32. The closed form
// only prunes the scan range (with a 1-pixel safety margin); the per-pixel
// float32 test decides membership, so cost stays ~the rectangle's area
// while parity stays exact.
inline void MaxRect(uint8_t* obs, float cx, float cy, float hw, float hh,
                    uint8_t v) {
  int x0 = std::max(0, (int)std::ceil((cx - hw) * kW - 0.5f) - 1);
  int x1 = std::min(kW - 1, (int)std::floor((cx + hw) * kW - 0.5f) + 1);
  int y0 = std::max(0, (int)std::ceil((cy - hh) * kH - 0.5f) - 1);
  int y1 = std::min(kH - 1, (int)std::floor((cy + hh) * kH - 0.5f) + 1);
  for (int y = y0; y <= y1; ++y) {
    float Yc = (y + 0.5f) / kH;
    if (std::fabs(Yc - cy) > hh) continue;
    for (int x = x0; x <= x1; ++x) {
      float Xc = (x + 0.5f) / kW;
      if (std::fabs(Xc - cx) <= hw)
        obs[y * kW + x] = std::max(obs[y * kW + x], v);
    }
  }
}

class SeaquestEnv : public Env {
 public:
  explicit SeaquestEnv(uint64_t seed) : rng_(seed) { Reset(); }

  void Reset() override {
    sub_x_ = sub_y_ = 0.5f;
    std::uniform_real_distribution<float> uni(0.f, 1.f);
    for (int i = 0; i < sq::kLanes; ++i) {
      fish_x_[i] = uni(rng_);
      fish_dir_[i] = uni(rng_) < 0.5f ? 1.f : -1.f;
      fish_alive_[i] = true;
    }
    torp_x_ = torp_y_ = 0.f;
    torp_dir_ = 1.f;
    torp_live_ = false;
    facing_ = 1.f;
    oxygen_ = sq::kOxyMax;
    lives_ = sq::kLives;
    t_ = 0;
  }

  StepOut Step(int action) override {
    StepOut out;
    for (int i = 0; i < kFrameSkip; ++i) out.reward += Substep(action);
    ++t_;
    if (lives_ <= 0 || t_ >= sq::kMaxT) {
      out.done = true;
      Reset();
    }
    return out;
  }

  void Render(uint8_t* obs) const override {
    namespace S = sq;
    std::memset(obs, 0, kH * kW);
    for (int y = 0; y < kH; ++y) {  // surface line
      float Yc = (y + 0.5f) / kH;
      if (std::fabs(Yc - S::kSurfaceY) < 0.012f)
        for (int x = 0; x < kW; ++x)
          obs[y * kW + x] = std::max<uint8_t>(obs[y * kW + x], 80);
    }
    float frac = std::clamp(oxygen_ / S::kOxyMax, 0.f, 1.f);
    for (int y = 0; y < kH; ++y) {  // oxygen bar
      float Yc = (y + 0.5f) / kH;
      if (Yc >= 0.04f) continue;
      for (int x = 0; x < kW; ++x)
        if ((x + 0.5f) / kW < frac)
          obs[y * kW + x] = std::max<uint8_t>(obs[y * kW + x], 140);
    }
    for (int i = 0; i < S::kLanes; ++i)
      if (fish_alive_[i])
        MaxRect(obs, fish_x_[i], S::kLaneY[i], S::kFishR, S::kFishR, 180);
    if (torp_live_) MaxRect(obs, torp_x_, torp_y_, 0.015f, 0.008f, 220);
    MaxRect(obs, sub_x_, sub_y_, S::kSubR, S::kSubR, 255);
  }

  int NumActions() const override { return sq::kNumActions; }

 private:
  float Substep(int action) {
    namespace S = sq;
    // actions: 0 noop, 1 fire, 2 up, 3 down, 4 left, 5 right
    float dx = (action == 5 ? 1.f : 0.f) - (action == 4 ? 1.f : 0.f);
    float dy = (action == 3 ? 1.f : 0.f) - (action == 2 ? 1.f : 0.f);
    bool fire = action == 1;
    if (dx != 0.f) facing_ = dx > 0 ? 1.f : -1.f;
    sub_x_ = std::clamp(sub_x_ + dx * S::kSubSpeed, 0.05f, 0.95f);
    sub_y_ = std::clamp(sub_y_ + dy * S::kSubSpeed, 0.08f, 0.92f);

    // fish advance; off-screen wraparound respawns (alive again)
    for (int i = 0; i < S::kLanes; ++i) {
      fish_x_[i] += fish_dir_[i] * S::kFishSpeed;
      if (fish_x_[i] < -0.05f || fish_x_[i] > 1.05f) {
        fish_x_[i] = fish_dir_[i] > 0 ? -0.05f : 1.05f;
        fish_alive_[i] = true;
      }
    }

    // torpedo (ordering mirrors seaquest.py _substep)
    bool was_live = torp_live_;
    bool live_new = torp_live_ || fire;
    if (was_live) {
      torp_x_ += torp_dir_ * S::kTorpSpeed;
    } else if (fire) {
      torp_x_ = sub_x_;
      torp_y_ = sub_y_;
    }
    if (!was_live) torp_dir_ = facing_;
    torp_live_ = live_new && torp_x_ > 0.f && torp_x_ < 1.f;

    float reward = 0.f;
    bool any_hit = false;
    for (int i = 0; i < S::kLanes; ++i) {
      bool hit = fish_alive_[i] && torp_live_ &&
                 std::fabs(fish_x_[i] - torp_x_) < S::kFishR + 0.02f &&
                 std::fabs(S::kLaneY[i] - torp_y_) < 0.04f;
      if (hit) {
        reward += S::kFishPoints;
        fish_alive_[i] = false;
        any_hit = true;
      }
    }
    if (any_hit) torp_live_ = false;

    bool collide = false;
    for (int i = 0; i < S::kLanes; ++i)
      collide = collide ||
                (fish_alive_[i] &&
                 std::fabs(fish_x_[i] - sub_x_) < S::kFishR + S::kSubR &&
                 std::fabs(S::kLaneY[i] - sub_y_) < S::kFishR + S::kSubR);

    bool surfaced = sub_y_ <= S::kSurfaceY;
    oxygen_ = surfaced ? std::min(oxygen_ + S::kOxyRefill, S::kOxyMax)
                       : oxygen_ - 1.f;
    bool suffocate = oxygen_ <= 0.f;

    if (collide || suffocate) {
      --lives_;
      sub_x_ = sub_y_ = 0.5f;
      oxygen_ = S::kOxyMax;
    }
    return reward;
  }

  std::mt19937_64 rng_;
  float sub_x_, sub_y_;
  float fish_x_[sq::kLanes], fish_dir_[sq::kLanes];
  bool fish_alive_[sq::kLanes];
  float torp_x_, torp_y_, torp_dir_;
  bool torp_live_;
  float facing_, oxygen_;
  int lives_, t_;
};

class QbertEnv : public Env {
 public:
  explicit QbertEnv(uint64_t seed) : rng_(seed) { Reset(); }

  void Reset() override {
    pos_r_ = pos_c_ = 0;
    std::fill(std::begin(flipped_), std::end(flipped_), false);
    ball_r_ = 1;
    ball_c_ = 0;
    ball_live_ = false;
    lives_ = qb::kLives;
    boards_ = 0;
    t_ = 0;
  }

  StepOut Step(int action) override {  // FRAME_SKIP=1: the hop IS the quantum
    namespace Q = qb;
    StepOut out;
    // hop: 1 up-right (-1,0), 2 down-right (+1,+1), 3 down-left (+1,0),
    // 4 up-left (-1,-1)
    int dr = (action == 2 || action == 3) ? 1 : (action == 1 || action == 4) ? -1 : 0;
    int dc = action == 2 ? 1 : action == 4 ? -1 : 0;
    bool moved = action != 0;
    int nr = pos_r_ + dr, nc = pos_c_ + dc;
    bool on_board = nr >= 0 && nr < Q::kRows && nc >= 0 && nc <= nr;
    bool fell = moved && !on_board;
    if (on_board) {
      pos_r_ = nr;
      pos_c_ = nc;
    }

    int idx = pos_r_ * (pos_r_ + 1) / 2 + pos_c_;
    bool newly = moved && on_board && !flipped_[idx];
    if (moved && on_board) flipped_[idx] = true;
    if (newly) out.reward += Q::kCubePoints;

    bool cleared = true;
    for (bool f : flipped_) cleared = cleared && f;
    if (cleared) {
      out.reward += Q::kClearBonus;
      std::fill(std::begin(flipped_), std::end(flipped_), false);
      ++boards_;
    }

    // enemy ball (mirrors qbert.py: spawn at (1,0), random diagonal descent)
    bool spawn = !ball_live_;
    int bdc = (int)(rng_() & 1);
    if (spawn) {
      ball_r_ = 1;
      ball_c_ = 0;
    } else {
      ball_r_ += 1;
      ball_c_ += bdc;
    }
    bool live = ball_r_ < Q::kRows;
    if (!live) {
      ball_r_ = 1;
      ball_c_ = 0;
    }
    ball_c_ = std::clamp(ball_c_, 0, ball_r_);

    bool caught = live && ball_r_ == pos_r_ && ball_c_ == pos_c_;
    if (fell || caught) {
      --lives_;
      pos_r_ = pos_c_ = 0;
    }
    ball_live_ = live || spawn;

    ++t_;
    if (lives_ <= 0 || t_ >= Q::kMaxT) {
      out.done = true;
      Reset();
    }
    return out;
  }

  void Render(uint8_t* obs) const override {
    namespace Q = qb;
    std::memset(obs, 0, kH * kW);
    for (int r = 0; r < Q::kRows; ++r)
      for (int c = 0; c <= r; ++c) {
        float cx = 0.5f + (c - r / 2.f) * 0.13f;
        float cy = 0.18f + r * 0.13f;
        int idx = r * (r + 1) / 2 + c;
        MaxRect(obs, cx, cy, 0.05f, 0.045f, flipped_[idx] ? 200 : 100);
      }
    float ax = 0.5f + (pos_c_ - pos_r_ / 2.f) * 0.13f;
    float ay = 0.18f + pos_r_ * 0.13f - 0.05f;
    MaxRect(obs, ax, ay, 0.025f, 0.025f, 255);
    if (ball_live_) {
      float bx = 0.5f + (ball_c_ - ball_r_ / 2.f) * 0.13f;
      float by = 0.18f + ball_r_ * 0.13f - 0.05f;
      MaxRect(obs, bx, by, 0.02f, 0.02f, 160);
    }
  }

  int NumActions() const override { return qb::kNumActions; }

 private:
  std::mt19937_64 rng_;
  int pos_r_, pos_c_;
  bool flipped_[qb::kCubes];
  int ball_r_, ball_c_;
  bool ball_live_;
  int lives_, boards_, t_;
};

class SpaceInvadersEnv : public Env {
 public:
  explicit SpaceInvadersEnv(uint64_t seed) : rng_(seed) { Reset(); }

  void Reset() override {
    namespace S = si;
    for (auto& a : aliens_) a = true;
    ox_ = 0.18f;
    oy_ = 0.12f;
    dir_ = 1.f;
    player_x_ = 0.5f;
    shot_live_ = false;
    shot_x_ = shot_y_ = 0.f;
    for (int i = 0; i < S::kNBombs; ++i) bomb_live_[i] = false;
    lives_ = S::kLives;
    t_ = 0;
  }

  StepOut Step(int action) override {
    StepOut out;
    for (int i = 0; i < kFrameSkip; ++i) out.reward += Substep(action);
    ++t_;
    if (lives_ <= 0 || t_ >= si::kMaxT) {
      out.done = true;
      Reset();
    }
    return out;
  }

  void Render(uint8_t* obs) const override {
    namespace S = si;
    std::memset(obs, 0, kH * kW);
    for (int r = 0; r < S::kRows; ++r)
      for (int c = 0; c < S::kCols; ++c)
        if (aliens_[r * S::kCols + c])
          MaxRect(obs, ox_ + c * S::kGridDX, oy_ + r * S::kGridDY,
                  S::kAlienW, S::kAlienH, 180);
    MaxRect(obs, player_x_, S::kPlayerY, S::kPlayerW, 0.02f, 255);
    if (shot_live_) MaxRect(obs, shot_x_, shot_y_, 0.006f, 0.015f, 255);
    for (int i = 0; i < S::kNBombs; ++i)
      if (bomb_live_[i])
        MaxRect(obs, bomb_x_[i], bomb_y_[i], 0.006f, 0.015f, 120);
  }

  int NumActions() const override { return si::kNumActions; }

 private:
  float Substep(int action) {
    namespace S = si;
    // 0 noop, 1 fire, 2 right, 3 left, 4 right+fire, 5 left+fire
    float move = (action == 2 || action == 4) ? 1.f
                 : (action == 3 || action == 5) ? -1.f : 0.f;
    bool fire = action == 1 || action == 4 || action == 5;
    player_x_ = std::clamp(player_x_ + move * S::kPlayerSpeed, S::kPlayerW,
                           1.f - S::kPlayerW);

    // march: faster as the fleet thins
    int alive = 0;
    for (bool a : aliens_) alive += a;
    float speed =
        S::kMarch * (1.f + 2.f * (1.f - (float)alive / (S::kRows * S::kCols)));
    float left = 1e9f, right = -1e9f;
    for (int c = 0; c < S::kCols; ++c) {
      bool any = false;
      for (int r = 0; r < S::kRows; ++r) any = any || aliens_[r * S::kCols + c];
      if (any) {
        left = std::min(left, ox_ + c * S::kGridDX);
        right = std::max(right, ox_ + c * S::kGridDX);
      }
    }
    bool edge = (right + S::kAlienW >= 0.98f && dir_ > 0) ||
                (left - S::kAlienW <= 0.02f && dir_ < 0);
    if (edge) {
      dir_ = -dir_;
      oy_ += S::kDescend;
    } else {
      ox_ += speed * dir_;
    }

    // player shot
    bool launch = fire && !shot_live_;
    if (launch) {
      shot_x_ = player_x_;
      shot_y_ = S::kPlayerY - 0.03f;
    }
    if (shot_live_ || launch) shot_y_ -= S::kShotSpeed;
    shot_live_ = (shot_live_ || launch) && shot_y_ > 0.f;

    // shot vs fleet (nearest cell, same rule as the jnp argmin lookup)
    float reward = 0.f;
    if (shot_live_) {
      int col = (int)std::lround((shot_x_ - ox_) / S::kGridDX);
      int row = (int)std::lround((shot_y_ - oy_) / S::kGridDY);
      col = std::clamp(col, 0, S::kCols - 1);
      row = std::clamp(row, 0, S::kRows - 1);
      bool in = std::fabs(ox_ + col * S::kGridDX - shot_x_) <= S::kAlienW &&
                std::fabs(oy_ + row * S::kGridDY - shot_y_) <= S::kAlienH;
      if (in && aliens_[row * S::kCols + col]) {
        aliens_[row * S::kCols + col] = false;
        reward += S::kRowPoints[row];
        shot_live_ = false;
      }
    }

    // bombs from the lowest live alien of a random column
    std::uniform_real_distribution<float> uni(0.f, 1.f);
    int bcol = (int)(rng_() % S::kCols);
    int low = -1;
    for (int r = S::kRows - 1; r >= 0; --r)
      if (aliens_[r * S::kCols + bcol]) {
        low = r;
        break;
      }
    int slot = -1;
    for (int i = 0; i < S::kNBombs; ++i)
      if (!bomb_live_[i]) {
        slot = i;
        break;
      }
    if (low >= 0 && slot >= 0 && uni(rng_) < S::kBombP) {
      bomb_live_[slot] = true;
      bomb_x_[slot] = ox_ + bcol * S::kGridDX;
      bomb_y_[slot] = oy_ + low * S::kGridDY + S::kAlienH;
    }
    // at most one life lost per substep, as in the jnp any() reduction
    bool any_hit = false;
    for (int i = 0; i < S::kNBombs; ++i) {
      if (!bomb_live_[i]) continue;
      bomb_y_[i] += S::kBombSpeed;
      bool hit = std::fabs(bomb_x_[i] - player_x_) <= S::kPlayerW &&
                 bomb_y_[i] >= S::kPlayerY - 0.02f;
      if (hit) {
        any_hit = true;
        bomb_live_[i] = false;
      } else if (bomb_y_[i] >= 1.f) {
        bomb_live_[i] = false;
      }
    }
    if (any_hit) --lives_;

    // fleet landed -> game over; wave cleared -> fresh, lower fleet
    for (int r = 0; r < S::kRows; ++r)
      for (int c = 0; c < S::kCols; ++c)
        if (aliens_[r * S::kCols + c] &&
            oy_ + r * S::kGridDY + S::kAlienH >= S::kPlayerY - 0.02f)
          lives_ = 0;
    bool any = false;
    for (bool a : aliens_) any = any || a;
    if (!any) {
      for (auto& a : aliens_) a = true;
      ox_ = 0.18f;
      oy_ = 0.16f;
    }
    return reward;
  }

  std::mt19937_64 rng_;
  bool aliens_[si::kRows * si::kCols];
  float ox_, oy_, dir_, player_x_;
  float shot_x_, shot_y_;
  bool shot_live_;
  float bomb_x_[si::kNBombs], bomb_y_[si::kNBombs];
  bool bomb_live_[si::kNBombs];
  int lives_, t_;
};

class BoxingEnv : public Env {
 public:
  explicit BoxingEnv(uint64_t seed) : rng_(seed) { Reset(); }

  void Reset() override {
    me_x_ = 0.3f;
    me_y_ = 0.5f;
    op_x_ = 0.7f;
    op_y_ = 0.5f;
    my_score_ = op_score_ = 0;
    my_cd_ = op_cd_ = 0;
    t_ = 0;
  }

  StepOut Step(int action) override {
    namespace B = bx;
    StepOut out;
    for (int i = 0; i < kFrameSkip; ++i) out.reward += Substep(action);
    ++t_;
    if (my_score_ >= B::kKo || op_score_ >= B::kKo || t_ >= B::kMaxT) {
      out.done = true;
      Reset();
    }
    return out;
  }

  void Render(uint8_t* obs) const override {
    namespace B = bx;
    std::memset(obs, 0, kH * kW);
    for (int y = 0; y < kH; ++y)
      for (int x = 0; x < kW; ++x) {
        float Xc = (x + 0.5f) / kW, Yc = (y + 0.5f) / kH;
        if (std::fabs(Xc - B::kRingLo) < 0.008f ||
            std::fabs(Xc - B::kRingHi) < 0.008f ||
            std::fabs(Yc - B::kRingLo) < 0.008f ||
            std::fabs(Yc - B::kRingHi) < 0.008f)
          obs[y * kW + x] = 80;
        if (Yc < 0.04f && Xc < (float)my_score_ / B::kKo)
          obs[y * kW + x] = 255;
        if (Yc > 0.96f && Xc < (float)op_score_ / B::kKo)
          obs[y * kW + x] = std::max<uint8_t>(obs[y * kW + x], 120);
      }
    MaxRect(obs, op_x_, op_y_, 0.03f, 0.03f, 150);
    MaxRect(obs, me_x_, me_y_, 0.03f, 0.03f, 255);
  }

  int NumActions() const override { return bx::kNumActions; }

 private:
  float Substep(int action) {
    namespace B = bx;
    // decode: 1 punch; 2..9 moves/diagonals; 10..17 punch+move
    static const float mv[10][2] = {{0, 0}, {0, 0},  {0, -1}, {1, 0}, {-1, 0},
                                    {0, 1}, {1, -1}, {-1, -1}, {1, 1}, {-1, 1}};
    bool combo = action >= 10;
    int base = std::clamp(combo ? action - 8 : action, 0, 9);
    bool punch = action == 1 || combo;
    me_x_ = std::clamp(me_x_ + mv[base][0] * B::kMove, B::kRingLo, B::kRingHi);
    me_y_ = std::clamp(me_y_ + mv[base][1] * B::kMove, B::kRingLo, B::kRingHi);

    std::uniform_real_distribution<float> uni(0.f, 1.f);
    float dx = me_x_ - op_x_, dy = me_y_ - op_y_;
    float dist = std::sqrt(dx * dx + dy * dy) + 1e-6f;
    op_x_ += dx / dist * B::kOppMove + (uni(rng_) - 0.5f) * B::kOppMove;
    op_y_ += dy / dist * B::kOppMove + (uni(rng_) - 0.5f) * B::kOppMove;
    op_x_ = std::clamp(op_x_, B::kRingLo, B::kRingHi);
    op_y_ = std::clamp(op_y_, B::kRingLo, B::kRingHi);

    // range test uses the POST-move distance (boxing.py computes
    // in_range from me-opp after the chase/jitter move); knockback below
    // keeps the pre-move dx/dist vector, also matching the JAX plane
    float pdx = me_x_ - op_x_, pdy = me_y_ - op_y_;
    bool in_range = std::sqrt(pdx * pdx + pdy * pdy) <= B::kPunchRange;
    bool my_land = punch && in_range && my_cd_ <= 0;
    bool op_land = uni(rng_) < B::kOppPunchP && in_range && op_cd_ <= 0;
    // knockback pushes the punched boxer AWAY from the puncher (dx = me-op)
    if (my_land) {
      ++my_score_;
      op_x_ = std::clamp(op_x_ + dx / dist * -0.05f, B::kRingLo, B::kRingHi);
      op_y_ = std::clamp(op_y_ + dy / dist * -0.05f, B::kRingLo, B::kRingHi);
    }
    if (op_land) {
      ++op_score_;
      me_x_ = std::clamp(me_x_ + dx / dist * 0.05f, B::kRingLo, B::kRingHi);
      me_y_ = std::clamp(me_y_ + dy / dist * 0.05f, B::kRingLo, B::kRingHi);
    }
    my_cd_ = my_land ? B::kPunchCd : std::max(my_cd_ - 1, 0);
    op_cd_ = op_land ? B::kPunchCd : std::max(op_cd_ - 1, 0);
    return (float)my_land - (float)op_land;
  }

  std::mt19937_64 rng_;
  float me_x_, me_y_, op_x_, op_y_;
  int my_score_, op_score_, my_cd_, op_cd_, t_;
};

class AssaultEnv : public Env {
 public:
  explicit AssaultEnv(uint64_t seed) : rng_(seed) { Reset(); }

  void Reset() override {
    namespace A = as_;
    mother_x_ = 0.5f;
    mother_dir_ = 1.f;
    for (int i = 0; i < A::kNLanes; ++i) att_live_[i] = false;
    bomb_live_ = false;
    player_x_ = 0.5f;
    shot_live_ = false;
    heat_ = 0.f;
    jammed_ = false;
    lives_ = A::kLives;
    t_ = 0;
  }

  StepOut Step(int action) override {
    StepOut out;
    for (int i = 0; i < kFrameSkip; ++i) out.reward += Substep(action);
    ++t_;
    if (lives_ <= 0 || t_ >= as_::kMaxT) {
      out.done = true;
      Reset();
    }
    return out;
  }

  void Render(uint8_t* obs) const override {
    namespace A = as_;
    std::memset(obs, 0, kH * kW);
    MaxRect(obs, mother_x_, A::kMotherY, A::kMotherW, 0.02f, 200);
    for (int i = 0; i < A::kNLanes; ++i)
      if (att_live_[i])
        MaxRect(obs, att_x_[i], att_y_[i], A::kAttW, A::kAttH, 160);
    MaxRect(obs, player_x_, A::kPlayerY, A::kPlayerW, 0.02f, 255);
    if (shot_live_) MaxRect(obs, shot_x_, shot_y_, 0.006f, 0.015f, 255);
    if (bomb_live_) MaxRect(obs, bomb_x_, bomb_y_, 0.008f, 0.012f, 120);
    for (int y = 0; y < kH; ++y) {  // heat gauge on the right edge
      float Yc = (y + 0.5f) / kH;
      if (Yc <= 1.f - heat_) continue;
      for (int x = 0; x < kW; ++x)
        if ((x + 0.5f) / kW > 0.97f)
          obs[y * kW + x] = std::max<uint8_t>(obs[y * kW + x], 90);
    }
  }

  int NumActions() const override { return as_::kNumActions; }

 private:
  float Substep(int action) {
    namespace A = as_;
    // 0 noop, 1 fire, 2 vent, 3 right, 4 left, 5 right+fire, 6 left+fire
    float move = (action == 3 || action == 5) ? 1.f
                 : (action == 4 || action == 6) ? -1.f : 0.f;
    bool fire = action == 1 || action == 5 || action == 6;
    bool vent = action == 2;
    player_x_ = std::clamp(player_x_ + move * A::kPlayerSpeed, A::kPlayerW,
                           1.f - A::kPlayerW);

    mother_x_ += mother_dir_ * A::kMotherSpeed;
    if (mother_x_ > 1.f - A::kMotherW || mother_x_ < A::kMotherW)
      mother_dir_ = -mother_dir_;
    mother_x_ = std::clamp(mother_x_, A::kMotherW, 1.f - A::kMotherW);

    std::uniform_real_distribution<float> uni(0.f, 1.f);
    int lane = (int)(rng_() % A::kNLanes);
    if (!att_live_[lane] && uni(rng_) < A::kSpawnP) {
      att_live_[lane] = true;
      att_x_[lane] = mother_x_;
      att_y_[lane] = A::kMotherY + 0.05f;
    }
    for (int i = 0; i < A::kNLanes; ++i) {
      if (!att_live_[i]) continue;
      att_x_[i] += (player_x_ > att_x_[i] ? 1.f : -1.f) * A::kStrafe;
      att_y_[i] += A::kDescend;
    }

    heat_ = std::max(heat_ - (vent ? A::kVentCool : A::kCool), 0.f);
    jammed_ = jammed_ && heat_ > 0.3f;
    bool can_fire = fire && !shot_live_ && !jammed_;
    if (can_fire) {
      heat_ += A::kHeatPerShot;
      shot_x_ = player_x_;
      shot_y_ = A::kPlayerY - 0.03f;
    }
    if (heat_ >= 1.f) jammed_ = true;
    heat_ = std::min(heat_, 1.f);
    if (shot_live_ || can_fire) shot_y_ -= A::kShotSpeed;
    shot_live_ = (shot_live_ || can_fire) && shot_y_ > 0.f;

    // the shot destroys EVERY overlapping attacker (jnp evaluates all hit
    // flags against the still-live shot, then consumes it once)
    float reward = 0.f;
    bool shot_hit = false;
    for (int i = 0; i < A::kNLanes; ++i) {
      bool hit = att_live_[i] && shot_live_ &&
                 std::fabs(att_x_[i] - shot_x_) <= A::kAttW &&
                 std::fabs(att_y_[i] - shot_y_) <= A::kAttH;
      if (hit) {
        reward += A::kAttackerPoints;
        att_live_[i] = false;
        shot_hit = true;
      }
    }
    if (shot_hit) shot_live_ = false;
    if (shot_live_ && std::fabs(mother_x_ - shot_x_) <= A::kMotherW &&
        shot_y_ <= A::kMotherY + 0.02f) {
      reward += A::kMotherPoints;
      shot_live_ = false;
    }

    int src = -1;
    for (int i = 0; i < A::kNLanes; ++i)
      if (att_live_[i]) {
        src = i;
        break;
      }
    if (!bomb_live_ && src >= 0 && uni(rng_) < A::kBombP) {
      bomb_live_ = true;
      bomb_x_ = att_x_[src];
      bomb_y_ = att_y_[src];
    }
    // at most one life lost per substep (jnp: bomb_hit | reached.any())
    bool player_hit = false;
    if (bomb_live_) {
      bomb_y_ += A::kBombSpeed;
      bool hit = std::fabs(bomb_x_ - player_x_) <= A::kPlayerW &&
                 bomb_y_ >= A::kPlayerY - 0.02f;
      if (hit) {
        player_hit = true;
        bomb_live_ = false;
      } else if (bomb_y_ >= 1.f) {
        bomb_live_ = false;
      }
    }
    for (int i = 0; i < A::kNLanes; ++i)
      if (att_live_[i] && att_y_[i] >= A::kPlayerY - 0.02f) {
        player_hit = true;
        att_live_[i] = false;
      }
    if (player_hit) --lives_;
    return reward;
  }

  std::mt19937_64 rng_;
  float mother_x_, mother_dir_;
  float att_x_[as_::kNLanes], att_y_[as_::kNLanes];
  bool att_live_[as_::kNLanes];
  float bomb_x_, bomb_y_;
  bool bomb_live_;
  float player_x_, shot_x_, shot_y_;
  bool shot_live_;
  float heat_;
  bool jammed_;
  int lives_, t_;
};

// ------------------------------------------------------------- batched ----
class BatchedEnv {
 public:
  BatchedEnv(const std::string& name, int n, uint64_t seed) {
    for (int i = 0; i < n; ++i) {
      if (name == "pong")
        envs_.emplace_back(new PongEnv(seed + i));
      else if (name == "breakout")
        envs_.emplace_back(new BreakoutEnv(seed + i));
      else if (name == "seaquest")
        envs_.emplace_back(new SeaquestEnv(seed + i));
      else if (name == "qbert")
        envs_.emplace_back(new QbertEnv(seed + i));
      else if (name == "space_invaders")
        envs_.emplace_back(new SpaceInvadersEnv(seed + i));
      else if (name == "boxing")
        envs_.emplace_back(new BoxingEnv(seed + i));
      else if (name == "assault")
        envs_.emplace_back(new AssaultEnv(seed + i));
      else
        envs_.clear();
      if (envs_.empty()) break;
    }
  }

  bool ok() const { return !envs_.empty(); }
  int size() const { return (int)envs_.size(); }
  int num_actions() const { return envs_[0]->NumActions(); }

  void ResetAll(uint8_t* obs) {
    for (size_t i = 0; i < envs_.size(); ++i) {
      envs_[i]->Reset();
      envs_[i]->Render(obs + i * kH * kW);
    }
  }

  // actions[n] -> obs[n*84*84], rewards[n], dones[n]
  void StepBatch(const int32_t* actions, uint8_t* obs, float* rewards,
                 uint8_t* dones) {
    const int n = (int)envs_.size();
    const int hw = kH * kW;
    auto work = [&](int lo, int hi) {
      for (int i = lo; i < hi; ++i) {
        StepOut out = envs_[i]->Step(actions[i]);
        rewards[i] = out.reward;
        dones[i] = out.done ? 1 : 0;
        envs_[i]->Render(obs + (size_t)i * hw);
      }
    };
    const int kThreadThreshold = 64;
    if (n < kThreadThreshold) {
      work(0, n);
      return;
    }
    int nt = std::min<int>(
        std::max(1u, std::thread::hardware_concurrency()), 8);
    std::vector<std::thread> threads;
    int chunk = (n + nt - 1) / nt;
    for (int t = 0; t < nt; ++t) {
      int lo = t * chunk, hi = std::min(n, lo + chunk);
      if (lo < hi) threads.emplace_back(work, lo, hi);
    }
    for (auto& th : threads) th.join();
  }

 private:
  std::vector<std::unique_ptr<Env>> envs_;
};

}  // namespace

// ------------------------------------------------------------- C API ------
extern "C" {

void* ba3c_env_create(const char* name, int n, uint64_t seed) {
  auto* b = new BatchedEnv(name, n, seed);
  if (!b->ok()) {
    delete b;
    return nullptr;
  }
  return b;
}

void ba3c_env_destroy(void* handle) { delete (BatchedEnv*)handle; }

int ba3c_env_num_actions(void* handle) {
  return ((BatchedEnv*)handle)->num_actions();
}

int ba3c_env_size(void* handle) { return ((BatchedEnv*)handle)->size(); }

void ba3c_env_reset(void* handle, uint8_t* obs) {
  ((BatchedEnv*)handle)->ResetAll(obs);
}

void ba3c_env_step(void* handle, const int32_t* actions, uint8_t* obs,
                   float* rewards, uint8_t* dones) {
  ((BatchedEnv*)handle)->StepBatch(actions, obs, rewards, dones);
}

int ba3c_obs_height() { return kH; }
int ba3c_obs_width() { return kW; }
}
