// Batched Atari-like environment core (native).
//
// The reference's principal native component is ALE — a C++ Atari 2600
// emulator whose per-frame cost dominates the actor side (SURVEY.md §2.10).
// This is its TPU-rebuild equivalent: game physics, frameskip and 84x84
// grayscale rendering in C++, with a BATCHED step API so one host process
// drives hundreds of envs per call (the reference paid one process per env).
//
// Game semantics intentionally mirror distributed_ba3c_tpu/envs/jaxenv/
// (pong.py, breakout.py): same geometry constants, action maps, reward
// structure (first-to-21 Pong; 6x18 bricks / 5 lives / row-scored Breakout),
// so policies transfer between the on-device JAX envs and this host-side
// core, and the Python tests can assert semantic parity.
//
// No external dependencies (the image has no zmq.h/msgpack.h): transport is
// thin pyzmq glue in distributed_ba3c_tpu/envs/native.py; every hot cycle
// (step physics + render) happens here.
//
// Build: make -C cpp   (g++ -O3 -shared -fPIC)

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <memory>
#include <random>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr int kH = 84, kW = 84;
constexpr int kFrameSkip = 4;

// ---------------------------------------------------------------- Pong ----
namespace pong {
constexpr float kPaddleH = 0.16f, kPaddleW = 0.02f;
constexpr float kAgentX = 0.95f, kOppX = 0.05f;
constexpr float kBallR = 0.015f;
constexpr float kPaddleSpeed = 0.05f, kOppSpeed = 0.035f, kBallSpeed = 0.04f;
constexpr int kWinScore = 21;
constexpr int kNumActions = 6;
}  // namespace pong

// ------------------------------------------------------------ Seaquest ----
// Mirrors distributed_ba3c_tpu/envs/jaxenv/seaquest.py: 4 enemy lanes,
// horizontal torpedoes, oxygen meter with surfacing, 3 lives.
namespace sq {
constexpr int kLanes = 4;
constexpr float kLaneY[kLanes] = {0.35f, 0.5f, 0.65f, 0.8f};
constexpr float kSurfaceY = 0.15f;
constexpr float kSubSpeed = 0.03f, kFishSpeed = 0.02f, kTorpSpeed = 0.08f;
constexpr float kSubR = 0.03f, kFishR = 0.025f;
constexpr float kOxyMax = 200.f, kOxyRefill = 8.f;
constexpr int kLives = 3;
constexpr float kFishPoints = 20.f;
constexpr int kMaxT = 5000;
constexpr int kNumActions = 6;
}  // namespace sq

// --------------------------------------------------------------- Q*bert ---
// Mirrors distributed_ba3c_tpu/envs/jaxenv/qbert.py: 21-cube pyramid,
// +25/new cube, +100/board clear, bouncing enemy ball, 3 lives, 1 hop/step.
namespace qb {
constexpr int kRows = 6;
constexpr int kCubes = kRows * (kRows + 1) / 2;  // 21
constexpr float kCubePoints = 25.f, kClearBonus = 100.f;
constexpr int kLives = 3, kMaxT = 2000, kNumActions = 5;
}  // namespace qb

// ------------------------------------------------------------ Breakout ----
namespace brk {
constexpr int kRows = 6, kCols = 18;
constexpr float kBrickTop = 0.15f, kBrickH = 0.03f;
constexpr float kPaddleY = 0.92f, kPaddleH = 0.02f, kPaddleW = 0.08f;
constexpr float kBallR = 0.012f;
constexpr float kPaddleSpeed = 0.04f, kBallSpeed = 0.035f;
constexpr int kLives = 5;
constexpr int kMaxT = 10000;
constexpr int kNumActions = 4;
constexpr float kRowPoints[kRows] = {7.f, 7.f, 4.f, 4.f, 1.f, 1.f};
}  // namespace brk

struct StepOut {
  float reward = 0.f;
  bool done = false;
};

// clamped-rect rasterizer shared by all games
void DrawRect(uint8_t* obs, float cx, float cy, float hw, float hh, uint8_t v) {
  int x0 = std::max(0, (int)std::floor((cx - hw) * kW));
  int x1 = std::min(kW - 1, (int)std::ceil((cx + hw) * kW));
  int y0 = std::max(0, (int)std::floor((cy - hh) * kH));
  int y1 = std::min(kH - 1, (int)std::ceil((cy + hh) * kH));
  for (int y = y0; y <= y1; ++y)
    for (int x = x0; x <= x1; ++x) obs[y * kW + x] = v;
}

class Env {
 public:
  virtual ~Env() = default;
  virtual void Reset() = 0;
  virtual StepOut Step(int action) = 0;  // one agent step (kFrameSkip ticks)
  virtual void Render(uint8_t* obs) const = 0;  // [kH * kW]
  virtual int NumActions() const = 0;
};

class PongEnv : public Env {
 public:
  explicit PongEnv(uint64_t seed) : rng_(seed) { Reset(); }

  void Reset() override {
    agent_y_ = opp_y_ = 0.5f;
    agent_score_ = opp_score_ = 0;
    Serve(/*towards_agent=*/true);
  }

  StepOut Step(int action) override {
    float move = 0.f;
    if (action == 2 || action == 4) move = -1.f;
    if (action == 3 || action == 5) move = 1.f;
    StepOut out;
    for (int i = 0; i < kFrameSkip; ++i) out.reward += Substep(move);
    if (agent_score_ >= pong::kWinScore || opp_score_ >= pong::kWinScore) {
      out.done = true;
      Reset();
    }
    return out;
  }

  void Render(uint8_t* obs) const override {
    std::memset(obs, 0, kH * kW);
    // walls
    for (int x = 0; x < kW; ++x) {
      obs[0 * kW + x] = obs[1 * kW + x] = 80;
      obs[(kH - 1) * kW + x] = obs[(kH - 2) * kW + x] = 80;
    }
    DrawRect(obs, bx_, by_, pong::kBallR, pong::kBallR, 255);
    DrawRect(obs, pong::kAgentX, agent_y_, pong::kPaddleW, pong::kPaddleH / 2, 255);
    DrawRect(obs, pong::kOppX, opp_y_, pong::kPaddleW, pong::kPaddleH / 2, 255);
  }

  int NumActions() const override { return pong::kNumActions; }

  int agent_score() const { return agent_score_; }
  int opp_score() const { return opp_score_; }

 private:
  void Serve(bool towards_agent) {
    std::uniform_real_distribution<float> ang(-0.7f, 0.7f);
    std::uniform_real_distribution<float> jit(-0.1f, 0.1f);
    float a = ang(rng_);
    bx_ = 0.5f;
    by_ = 0.5f + jit(rng_);
    vx_ = pong::kBallSpeed * std::cos(a) * (towards_agent ? 1.f : -1.f);
    vy_ = pong::kBallSpeed * std::sin(a);
  }

  float Substep(float move) {
    namespace P = pong;
    agent_y_ = std::clamp(agent_y_ + move * P::kPaddleSpeed, P::kPaddleH / 2,
                          1.f - P::kPaddleH / 2);
    float opp_dy = std::clamp(by_ - opp_y_, -P::kOppSpeed, P::kOppSpeed);
    opp_y_ = std::clamp(opp_y_ + opp_dy, P::kPaddleH / 2, 1.f - P::kPaddleH / 2);

    bx_ += vx_;
    by_ += vy_;
    if (by_ < P::kBallR || by_ > 1.f - P::kBallR) {
      vy_ = -vy_;
      by_ = std::clamp(by_, P::kBallR, 1.f - P::kBallR);
    }
    // agent paddle (right, ball moving right)
    if (vx_ > 0 && bx_ >= P::kAgentX - P::kPaddleW &&
        std::fabs(by_ - agent_y_) <= P::kPaddleH / 2 + P::kBallR) {
      float off = (by_ - agent_y_) / (P::kPaddleH / 2);
      vx_ = -vx_;
      vy_ = P::kBallSpeed * 0.9f * off;
      bx_ = P::kAgentX - P::kPaddleW - P::kBallR;
    }
    // opponent paddle (left, ball moving left)
    if (vx_ < 0 && bx_ <= P::kOppX + P::kPaddleW &&
        std::fabs(by_ - opp_y_) <= P::kPaddleH / 2 + P::kBallR) {
      float off = (by_ - opp_y_) / (P::kPaddleH / 2);
      vx_ = -vx_;
      vy_ = P::kBallSpeed * 0.9f * off;
      bx_ = P::kOppX + P::kPaddleW + P::kBallR;
    }
    float reward = 0.f;
    if (bx_ <= 0.f) {  // opponent missed
      reward = 1.f;
      ++agent_score_;
      Serve(/*towards_agent=*/false);
    } else if (bx_ >= 1.f) {  // agent missed
      reward = -1.f;
      ++opp_score_;
      Serve(/*towards_agent=*/true);
    }
    return reward;
  }

  std::mt19937_64 rng_;
  float bx_, by_, vx_, vy_, agent_y_, opp_y_;
  int agent_score_, opp_score_;
};

class BreakoutEnv : public Env {
 public:
  explicit BreakoutEnv(uint64_t seed) : rng_(seed) { Reset(); }

  void Reset() override {
    paddle_x_ = 0.5f;
    bx_ = 0.5f;
    by_ = brk::kPaddleY - 0.05f;
    vx_ = vy_ = 0.f;
    lives_ = brk::kLives;
    in_play_ = false;
    t_ = 0;
    std::fill(std::begin(bricks_), std::end(bricks_), true);
  }

  StepOut Step(int action) override {
    float move = action == 2 ? 1.f : action == 3 ? -1.f : 0.f;
    bool fire = action == 1;
    StepOut out;
    for (int i = 0; i < kFrameSkip; ++i) out.reward += Substep(move, fire);
    ++t_;
    if (lives_ <= 0 || t_ >= brk::kMaxT) {
      out.done = true;
      Reset();
    }
    return out;
  }

  void Render(uint8_t* obs) const override {
    namespace B = brk;
    std::memset(obs, 0, kH * kW);
    for (int x = 0; x < kW; ++x) obs[0 * kW + x] = obs[1 * kW + x] = 80;
    // bricks
    for (int r = 0; r < B::kRows; ++r) {
      int y0 = (int)std::floor((B::kBrickTop + r * B::kBrickH) * kH);
      int y1 = (int)std::floor((B::kBrickTop + (r + 1) * B::kBrickH) * kH) - 1;
      for (int c = 0; c < B::kCols; ++c) {
        if (!bricks_[r * B::kCols + c]) continue;
        int x0 = c * kW / B::kCols;
        int x1 = (c + 1) * kW / B::kCols - 1;
        for (int y = std::max(0, y0); y <= std::min(kH - 1, y1); ++y)
          for (int x = x0; x <= x1; ++x) obs[y * kW + x] = 180;
      }
    }
    DrawRect(obs, bx_, by_, B::kBallR, B::kBallR, 255);
    DrawRect(obs, paddle_x_, B::kPaddleY, B::kPaddleW / 2, B::kPaddleH, 255);
  }

  int NumActions() const override { return brk::kNumActions; }
  int lives() const { return lives_; }
  int bricks_left() const {
    int n = 0;
    for (bool b : bricks_) n += b;
    return n;
  }

 private:
  float Substep(float move, bool fire) {
    namespace B = brk;
    paddle_x_ = std::clamp(paddle_x_ + move * B::kPaddleSpeed, B::kPaddleW / 2,
                           1.f - B::kPaddleW / 2);
    if (!in_play_) {
      bx_ = paddle_x_;
      by_ = B::kPaddleY - 0.05f;
      if (fire) {
        std::uniform_real_distribution<float> ang(0.25f * (float)M_PI,
                                                  0.75f * (float)M_PI);
        float a = ang(rng_);
        vx_ = B::kBallSpeed * std::cos(a);
        vy_ = -B::kBallSpeed * std::sin(a);
        in_play_ = true;
      }
      return 0.f;
    }
    bx_ += vx_;
    by_ += vy_;
    if (bx_ < B::kBallR || bx_ > 1.f - B::kBallR) {
      vx_ = -vx_;
      bx_ = std::clamp(bx_, B::kBallR, 1.f - B::kBallR);
    }
    if (by_ < B::kBallR) {
      vy_ = -vy_;
      by_ = B::kBallR;
    }
    // paddle
    if (vy_ > 0 && by_ >= B::kPaddleY - B::kPaddleH &&
        std::fabs(bx_ - paddle_x_) <= B::kPaddleW / 2 + B::kBallR) {
      float off = (bx_ - paddle_x_) / (B::kPaddleW / 2);
      vx_ = B::kBallSpeed * off;
      vy_ = -std::fabs(vy_);
      by_ = B::kPaddleY - B::kPaddleH - B::kBallR;
    }
    // bricks
    float reward = 0.f;
    int row = (int)std::floor((by_ - B::kBrickTop) / B::kBrickH);
    int col = (int)std::floor(bx_ * B::kCols);
    if (row >= 0 && row < B::kRows && col >= 0 && col < B::kCols &&
        bricks_[row * B::kCols + col]) {
      bricks_[row * B::kCols + col] = false;
      reward = B::kRowPoints[row];
      // reflect AND expel (see jaxenv/breakout.py: the drilling bug)
      bool from_below = vy_ < 0;
      by_ = from_below ? B::kBrickTop + (row + 1) * B::kBrickH + B::kBallR
                       : B::kBrickTop + row * B::kBrickH - B::kBallR;
      vy_ = -vy_;
      if (bricks_left() == 0)
        std::fill(std::begin(bricks_), std::end(bricks_), true);
    }
    // ball lost
    if (by_ >= 1.f - 1e-6f) {
      --lives_;
      in_play_ = false;
      vx_ = vy_ = 0.f;
      bx_ = paddle_x_;
      by_ = B::kPaddleY - 0.05f;
    }
    return reward;
  }

  std::mt19937_64 rng_;
  float bx_, by_, vx_, vy_, paddle_x_;
  bool bricks_[brk::kRows * brk::kCols];
  int lives_, t_;
  bool in_play_;
};

// jax-parity rasterizer: pixel-center inequality |Xc-cx|<=hw in float32,
// EXACTLY as the jnp renders evaluate it (envs/jaxenv/seaquest.py etc.) —
// closed-form ceil/floor bounds can disagree by one boundary pixel because
// (cx+hw)*kW and (x+0.5)/kW round differently in float32. The closed form
// only prunes the scan range (with a 1-pixel safety margin); the per-pixel
// float32 test decides membership, so cost stays ~the rectangle's area
// while parity stays exact.
inline void MaxRect(uint8_t* obs, float cx, float cy, float hw, float hh,
                    uint8_t v) {
  int x0 = std::max(0, (int)std::ceil((cx - hw) * kW - 0.5f) - 1);
  int x1 = std::min(kW - 1, (int)std::floor((cx + hw) * kW - 0.5f) + 1);
  int y0 = std::max(0, (int)std::ceil((cy - hh) * kH - 0.5f) - 1);
  int y1 = std::min(kH - 1, (int)std::floor((cy + hh) * kH - 0.5f) + 1);
  for (int y = y0; y <= y1; ++y) {
    float Yc = (y + 0.5f) / kH;
    if (std::fabs(Yc - cy) > hh) continue;
    for (int x = x0; x <= x1; ++x) {
      float Xc = (x + 0.5f) / kW;
      if (std::fabs(Xc - cx) <= hw)
        obs[y * kW + x] = std::max(obs[y * kW + x], v);
    }
  }
}

class SeaquestEnv : public Env {
 public:
  explicit SeaquestEnv(uint64_t seed) : rng_(seed) { Reset(); }

  void Reset() override {
    sub_x_ = sub_y_ = 0.5f;
    std::uniform_real_distribution<float> uni(0.f, 1.f);
    for (int i = 0; i < sq::kLanes; ++i) {
      fish_x_[i] = uni(rng_);
      fish_dir_[i] = uni(rng_) < 0.5f ? 1.f : -1.f;
      fish_alive_[i] = true;
    }
    torp_x_ = torp_y_ = 0.f;
    torp_dir_ = 1.f;
    torp_live_ = false;
    facing_ = 1.f;
    oxygen_ = sq::kOxyMax;
    lives_ = sq::kLives;
    t_ = 0;
  }

  StepOut Step(int action) override {
    StepOut out;
    for (int i = 0; i < kFrameSkip; ++i) out.reward += Substep(action);
    ++t_;
    if (lives_ <= 0 || t_ >= sq::kMaxT) {
      out.done = true;
      Reset();
    }
    return out;
  }

  void Render(uint8_t* obs) const override {
    namespace S = sq;
    std::memset(obs, 0, kH * kW);
    for (int y = 0; y < kH; ++y) {  // surface line
      float Yc = (y + 0.5f) / kH;
      if (std::fabs(Yc - S::kSurfaceY) < 0.012f)
        for (int x = 0; x < kW; ++x)
          obs[y * kW + x] = std::max<uint8_t>(obs[y * kW + x], 80);
    }
    float frac = std::clamp(oxygen_ / S::kOxyMax, 0.f, 1.f);
    for (int y = 0; y < kH; ++y) {  // oxygen bar
      float Yc = (y + 0.5f) / kH;
      if (Yc >= 0.04f) continue;
      for (int x = 0; x < kW; ++x)
        if ((x + 0.5f) / kW < frac)
          obs[y * kW + x] = std::max<uint8_t>(obs[y * kW + x], 140);
    }
    for (int i = 0; i < S::kLanes; ++i)
      if (fish_alive_[i])
        MaxRect(obs, fish_x_[i], S::kLaneY[i], S::kFishR, S::kFishR, 180);
    if (torp_live_) MaxRect(obs, torp_x_, torp_y_, 0.015f, 0.008f, 220);
    MaxRect(obs, sub_x_, sub_y_, S::kSubR, S::kSubR, 255);
  }

  int NumActions() const override { return sq::kNumActions; }

 private:
  float Substep(int action) {
    namespace S = sq;
    // actions: 0 noop, 1 fire, 2 up, 3 down, 4 left, 5 right
    float dx = (action == 5 ? 1.f : 0.f) - (action == 4 ? 1.f : 0.f);
    float dy = (action == 3 ? 1.f : 0.f) - (action == 2 ? 1.f : 0.f);
    bool fire = action == 1;
    if (dx != 0.f) facing_ = dx > 0 ? 1.f : -1.f;
    sub_x_ = std::clamp(sub_x_ + dx * S::kSubSpeed, 0.05f, 0.95f);
    sub_y_ = std::clamp(sub_y_ + dy * S::kSubSpeed, 0.08f, 0.92f);

    // fish advance; off-screen wraparound respawns (alive again)
    for (int i = 0; i < S::kLanes; ++i) {
      fish_x_[i] += fish_dir_[i] * S::kFishSpeed;
      if (fish_x_[i] < -0.05f || fish_x_[i] > 1.05f) {
        fish_x_[i] = fish_dir_[i] > 0 ? -0.05f : 1.05f;
        fish_alive_[i] = true;
      }
    }

    // torpedo (ordering mirrors seaquest.py _substep)
    bool was_live = torp_live_;
    bool live_new = torp_live_ || fire;
    if (was_live) {
      torp_x_ += torp_dir_ * S::kTorpSpeed;
    } else if (fire) {
      torp_x_ = sub_x_;
      torp_y_ = sub_y_;
    }
    if (!was_live) torp_dir_ = facing_;
    torp_live_ = live_new && torp_x_ > 0.f && torp_x_ < 1.f;

    float reward = 0.f;
    bool any_hit = false;
    for (int i = 0; i < S::kLanes; ++i) {
      bool hit = fish_alive_[i] && torp_live_ &&
                 std::fabs(fish_x_[i] - torp_x_) < S::kFishR + 0.02f &&
                 std::fabs(S::kLaneY[i] - torp_y_) < 0.04f;
      if (hit) {
        reward += S::kFishPoints;
        fish_alive_[i] = false;
        any_hit = true;
      }
    }
    if (any_hit) torp_live_ = false;

    bool collide = false;
    for (int i = 0; i < S::kLanes; ++i)
      collide = collide ||
                (fish_alive_[i] &&
                 std::fabs(fish_x_[i] - sub_x_) < S::kFishR + S::kSubR &&
                 std::fabs(S::kLaneY[i] - sub_y_) < S::kFishR + S::kSubR);

    bool surfaced = sub_y_ <= S::kSurfaceY;
    oxygen_ = surfaced ? std::min(oxygen_ + S::kOxyRefill, S::kOxyMax)
                       : oxygen_ - 1.f;
    bool suffocate = oxygen_ <= 0.f;

    if (collide || suffocate) {
      --lives_;
      sub_x_ = sub_y_ = 0.5f;
      oxygen_ = S::kOxyMax;
    }
    return reward;
  }

  std::mt19937_64 rng_;
  float sub_x_, sub_y_;
  float fish_x_[sq::kLanes], fish_dir_[sq::kLanes];
  bool fish_alive_[sq::kLanes];
  float torp_x_, torp_y_, torp_dir_;
  bool torp_live_;
  float facing_, oxygen_;
  int lives_, t_;
};

class QbertEnv : public Env {
 public:
  explicit QbertEnv(uint64_t seed) : rng_(seed) { Reset(); }

  void Reset() override {
    pos_r_ = pos_c_ = 0;
    std::fill(std::begin(flipped_), std::end(flipped_), false);
    ball_r_ = 1;
    ball_c_ = 0;
    ball_live_ = false;
    lives_ = qb::kLives;
    boards_ = 0;
    t_ = 0;
  }

  StepOut Step(int action) override {  // FRAME_SKIP=1: the hop IS the quantum
    namespace Q = qb;
    StepOut out;
    // hop: 1 up-right (-1,0), 2 down-right (+1,+1), 3 down-left (+1,0),
    // 4 up-left (-1,-1)
    int dr = (action == 2 || action == 3) ? 1 : (action == 1 || action == 4) ? -1 : 0;
    int dc = action == 2 ? 1 : action == 4 ? -1 : 0;
    bool moved = action != 0;
    int nr = pos_r_ + dr, nc = pos_c_ + dc;
    bool on_board = nr >= 0 && nr < Q::kRows && nc >= 0 && nc <= nr;
    bool fell = moved && !on_board;
    if (on_board) {
      pos_r_ = nr;
      pos_c_ = nc;
    }

    int idx = pos_r_ * (pos_r_ + 1) / 2 + pos_c_;
    bool newly = moved && on_board && !flipped_[idx];
    if (moved && on_board) flipped_[idx] = true;
    if (newly) out.reward += Q::kCubePoints;

    bool cleared = true;
    for (bool f : flipped_) cleared = cleared && f;
    if (cleared) {
      out.reward += Q::kClearBonus;
      std::fill(std::begin(flipped_), std::end(flipped_), false);
      ++boards_;
    }

    // enemy ball (mirrors qbert.py: spawn at (1,0), random diagonal descent)
    bool spawn = !ball_live_;
    int bdc = (int)(rng_() & 1);
    if (spawn) {
      ball_r_ = 1;
      ball_c_ = 0;
    } else {
      ball_r_ += 1;
      ball_c_ += bdc;
    }
    bool live = ball_r_ < Q::kRows;
    if (!live) {
      ball_r_ = 1;
      ball_c_ = 0;
    }
    ball_c_ = std::clamp(ball_c_, 0, ball_r_);

    bool caught = live && ball_r_ == pos_r_ && ball_c_ == pos_c_;
    if (fell || caught) {
      --lives_;
      pos_r_ = pos_c_ = 0;
    }
    ball_live_ = live || spawn;

    ++t_;
    if (lives_ <= 0 || t_ >= Q::kMaxT) {
      out.done = true;
      Reset();
    }
    return out;
  }

  void Render(uint8_t* obs) const override {
    namespace Q = qb;
    std::memset(obs, 0, kH * kW);
    for (int r = 0; r < Q::kRows; ++r)
      for (int c = 0; c <= r; ++c) {
        float cx = 0.5f + (c - r / 2.f) * 0.13f;
        float cy = 0.18f + r * 0.13f;
        int idx = r * (r + 1) / 2 + c;
        MaxRect(obs, cx, cy, 0.05f, 0.045f, flipped_[idx] ? 200 : 100);
      }
    float ax = 0.5f + (pos_c_ - pos_r_ / 2.f) * 0.13f;
    float ay = 0.18f + pos_r_ * 0.13f - 0.05f;
    MaxRect(obs, ax, ay, 0.025f, 0.025f, 255);
    if (ball_live_) {
      float bx = 0.5f + (ball_c_ - ball_r_ / 2.f) * 0.13f;
      float by = 0.18f + ball_r_ * 0.13f - 0.05f;
      MaxRect(obs, bx, by, 0.02f, 0.02f, 160);
    }
  }

  int NumActions() const override { return qb::kNumActions; }

 private:
  std::mt19937_64 rng_;
  int pos_r_, pos_c_;
  bool flipped_[qb::kCubes];
  int ball_r_, ball_c_;
  bool ball_live_;
  int lives_, boards_, t_;
};

// ------------------------------------------------------------- batched ----
class BatchedEnv {
 public:
  BatchedEnv(const std::string& name, int n, uint64_t seed) {
    for (int i = 0; i < n; ++i) {
      if (name == "pong")
        envs_.emplace_back(new PongEnv(seed + i));
      else if (name == "breakout")
        envs_.emplace_back(new BreakoutEnv(seed + i));
      else if (name == "seaquest")
        envs_.emplace_back(new SeaquestEnv(seed + i));
      else if (name == "qbert")
        envs_.emplace_back(new QbertEnv(seed + i));
      else
        envs_.clear();
      if (envs_.empty()) break;
    }
  }

  bool ok() const { return !envs_.empty(); }
  int size() const { return (int)envs_.size(); }
  int num_actions() const { return envs_[0]->NumActions(); }

  void ResetAll(uint8_t* obs) {
    for (size_t i = 0; i < envs_.size(); ++i) {
      envs_[i]->Reset();
      envs_[i]->Render(obs + i * kH * kW);
    }
  }

  // actions[n] -> obs[n*84*84], rewards[n], dones[n]
  void StepBatch(const int32_t* actions, uint8_t* obs, float* rewards,
                 uint8_t* dones) {
    const int n = (int)envs_.size();
    const int hw = kH * kW;
    auto work = [&](int lo, int hi) {
      for (int i = lo; i < hi; ++i) {
        StepOut out = envs_[i]->Step(actions[i]);
        rewards[i] = out.reward;
        dones[i] = out.done ? 1 : 0;
        envs_[i]->Render(obs + (size_t)i * hw);
      }
    };
    const int kThreadThreshold = 64;
    if (n < kThreadThreshold) {
      work(0, n);
      return;
    }
    int nt = std::min<int>(
        std::max(1u, std::thread::hardware_concurrency()), 8);
    std::vector<std::thread> threads;
    int chunk = (n + nt - 1) / nt;
    for (int t = 0; t < nt; ++t) {
      int lo = t * chunk, hi = std::min(n, lo + chunk);
      if (lo < hi) threads.emplace_back(work, lo, hi);
    }
    for (auto& th : threads) th.join();
  }

 private:
  std::vector<std::unique_ptr<Env>> envs_;
};

}  // namespace

// ------------------------------------------------------------- C API ------
extern "C" {

void* ba3c_env_create(const char* name, int n, uint64_t seed) {
  auto* b = new BatchedEnv(name, n, seed);
  if (!b->ok()) {
    delete b;
    return nullptr;
  }
  return b;
}

void ba3c_env_destroy(void* handle) { delete (BatchedEnv*)handle; }

int ba3c_env_num_actions(void* handle) {
  return ((BatchedEnv*)handle)->num_actions();
}

int ba3c_env_size(void* handle) { return ((BatchedEnv*)handle)->size(); }

void ba3c_env_reset(void* handle, uint8_t* obs) {
  ((BatchedEnv*)handle)->ResetAll(obs);
}

void ba3c_env_step(void* handle, const int32_t* actions, uint8_t* obs,
                   float* rewards, uint8_t* dones) {
  ((BatchedEnv*)handle)->StepBatch(actions, obs, rewards, dones);
}

int ba3c_obs_height() { return kH; }
int ba3c_obs_width() { return kW; }
}
